"""Multi-host bootstrap + window-sharded analytics (the stream analog of
context parallelism).

Two concerns the reference solves with external infrastructure:

1. **Cluster bootstrap.** The reference joins processes through ZooKeeper +
   Kafka consumer-group rebalancing (ZookeeperManager.java:29,
   MicroserviceKafkaConsumer.java). A TPU pod slice instead forms one SPMD
   program over all hosts' chips: `initialize()` wraps
   `jax.distributed.initialize` (coordinator/process env auto-detected on
   Cloud TPU; explicit for DCN clusters) and `make_global_mesh()` builds a
   mesh spanning every process's devices — ICI inside a slice, DCN between
   slices, exactly the layering SURVEY.md §2.5 prescribes.

2. **Window-sharded replay analytics.** SURVEY.md §5: this workload's
   "long context" is the unbounded event stream; its sequence-parallel
   analog shards the replay window across chips. `sharded_windowed_stats`
   splits the event rows of a replay across the mesh, folds each shard into
   a [K, W] stat grid locally (segment reductions — analytics/windows.py),
   and combines the partial grids with collectives: `psum`-family trees or
   an explicit `ppermute` ring (ring-attention's communication pattern,
   profitable when the grid is large and ICI hops should stay
   neighbor-to-neighbor).
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from sitewhere_tpu.analytics.windows import WindowedStats, _windowed_stats_impl
from sitewhere_tpu.parallel.mesh import SHARD_AXIS


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join (or form) the multi-host JAX cluster.

    On Cloud TPU pods every argument auto-detects from the metadata server;
    on DCN clusters pass coordinator ("host:port"), process count and id (or
    set SWTPU_COORDINATOR / SWTPU_NUM_PROCESSES / SWTPU_PROCESS_ID). Returns
    True if distributed mode was initialized, False for single-process runs
    (no coordinator configured) — callers need no special-casing either way.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "SWTPU_COORDINATOR")
    if num_processes is None and "SWTPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["SWTPU_NUM_PROCESSES"])
    if process_id is None and "SWTPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["SWTPU_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        in_pod = bool(os.environ.get("TPU_WORKER_HOSTNAMES"))
        if not in_pod:
            return False  # single host, nothing to join
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def make_global_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """Mesh over every device of every process (1-D shard axis). Under
    `jax.distributed` this spans hosts; single-process it equals
    parallel.mesh.make_mesh()."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devs), (SHARD_AXIS,))


@lru_cache(maxsize=1)
def live_mesh() -> Optional[Mesh]:
    """The default replay mesh for the serving planner: a 1-D shard mesh
    over every visible device, or None when only one device exists (a
    single chip gains nothing from sharded replay — the host kernel plus
    one dispatch already wins). Cached: device topology is fixed for the
    process lifetime."""
    devs = jax.devices()
    if len(devs) < 2:
        return None
    return Mesh(np.asarray(devs), (SHARD_AXIS,))


def process_shard_indices(mesh: Mesh) -> np.ndarray:
    """Shard indices whose devices live on THIS process — the shards this
    host's ingest threads must feed (the multi-host data-loading contract:
    each host device_puts only its addressable shards)."""
    me = jax.process_index()
    return np.asarray([i for i, d in enumerate(mesh.devices.flat)
                       if d.process_index == me], np.int32)


# -- window-sharded analytics -------------------------------------------------

def _combine_ring(stats: WindowedStats, axis: str,
                  size: Optional[int] = None) -> WindowedStats:
    """Ring all-reduce of partial stat grids via ppermute: S-1 steps, each
    passing the accumulated grid to the right neighbor. Communication
    pattern of ring attention (neighbor-only ICI hops), applied to the
    stream-window analog. `size` is the static mesh axis size (callers
    under shard_map pass it; jax.lax.axis_size only exists on jax >= 0.6)."""
    if size is None:
        size = jax.lax.axis_size(axis)
    perm = [(i, (i + 1) % size) for i in range(size)]

    def step(_, carry):
        acc_count, acc_sum, acc_min, acc_max, cur = carry
        nxt = tuple(jax.lax.ppermute(x, axis, perm) for x in cur)
        return (acc_count + nxt[0], acc_sum + nxt[1],
                jnp.minimum(acc_min, nxt[2]), jnp.maximum(acc_max, nxt[3]),
                nxt)

    local = (stats.count, stats.sum,
             jnp.where(stats.count == 0, jnp.inf, stats.min),
             jnp.where(stats.count == 0, -jnp.inf, stats.max))
    init = (local[0], local[1], local[2], local[3], local)
    count, vsum, vmin, vmax, _ = jax.lax.fori_loop(0, size - 1, step, init)
    return _finalize(count, vsum, vmin, vmax)


def _combine_psum(stats: WindowedStats, axis: str) -> WindowedStats:
    count = jax.lax.psum(stats.count, axis)
    vsum = jax.lax.psum(stats.sum, axis)
    vmin = jax.lax.pmin(jnp.where(stats.count == 0, jnp.inf, stats.min), axis)
    vmax = jax.lax.pmax(jnp.where(stats.count == 0, -jnp.inf, stats.max),
                        axis)
    return _finalize(count, vsum, vmin, vmax)


def _finalize(count, vsum, vmin, vmax) -> WindowedStats:
    empty = count == 0
    nan = jnp.float32(jnp.nan)
    return WindowedStats(
        count=count.astype(jnp.int32), sum=vsum.astype(jnp.float32),
        mean=jnp.where(empty, nan,
                       vsum / jnp.maximum(count, 1)).astype(jnp.float32),
        min=jnp.where(empty, nan, vmin).astype(jnp.float32),
        max=jnp.where(empty, nan, vmax).astype(jnp.float32))


def sharded_windowed_stats(keys, ts_rel, value, valid, *, window_ms: int,
                           num_keys: int, n_windows: int, mesh: Mesh,
                           combine: str = "psum") -> WindowedStats:
    """windowed_stats over a mesh: replay rows sharded across devices, the
    [K, W] grid combined by collective (`combine` = "psum" | "ring").

    Row padding to a multiple of the mesh size is handled here (padding rows
    are invalid). Returns replicated global stats.
    """
    if combine not in ("psum", "ring"):
        raise ValueError(f"combine {combine!r}: expected 'psum' or 'ring'")
    S = mesh.shape[SHARD_AXIS]
    keys = np.asarray(keys, np.int32)
    ts_rel = np.asarray(ts_rel, np.int32)
    value = np.asarray(value, np.float32)
    valid = np.asarray(valid, bool)
    B = keys.shape[0]
    Bp = -(-max(B, 1) // S) * S

    def pad(a, fill=0):
        out = np.full(Bp, fill, a.dtype)
        out[:B] = a
        return out

    ks = pad(keys).reshape(S, -1)
    ts = pad(ts_rel).reshape(S, -1)
    vals = pad(value).reshape(S, -1)
    ok = pad(valid, False).reshape(S, -1)

    run = _compiled_sharded_stats(mesh, combine, int(num_keys),
                                  int(n_windows))
    shard0 = NamedSharding(mesh, P(SHARD_AXIS))
    return run(jax.device_put(ks, shard0), jax.device_put(ts, shard0),
               jax.device_put(vals, shard0), jax.device_put(ok, shard0),
               jnp.asarray(window_ms, jnp.int32))


@lru_cache(maxsize=64)
def _compiled_sharded_stats(mesh: Mesh, combine: str, num_keys: int,
                            n_windows: int):
    """One jitted executable per (mesh, combine, grid shape) — same static-
    shape bucketing contract as analytics.windows._compiled_stats, so
    repeated replays reuse the compiled program instead of retracing."""
    from functools import partial as _partial

    combiner = (_combine_psum if combine == "psum" else
                _partial(_combine_ring, size=mesh.shape[SHARD_AXIS]))

    def shard_fn(k, t, v, m, w):
        local = _windowed_stats_impl(k[0], t[0], v[0], m[0], w,
                                     num_keys, n_windows)
        return combiner(local, SHARD_AXIS)

    specs = dict(
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                  P(SHARD_AXIS), P()),
        out_specs=WindowedStats(count=P(), sum=P(), mean=P(), min=P(),
                                max=P()))
    try:
        # the ring combine's replication is a loop invariant the checker
        # cannot infer statically
        mapped = _shard_map(shard_fn, check_vma=False, **specs)
    except TypeError:  # older jax spells it check_rep
        mapped = _shard_map(shard_fn, check_rep=False, **specs)
    return jax.jit(mapped)
