"""Multi-chip scaling: device mesh, shard routing, collective step.

The reference scales by running N replicas of each microservice and letting
Kafka consumer groups split topic partitions among them (SURVEY.md §2.5).
Here the same data parallelism is SPMD over a `jax.sharding.Mesh`: the device
dimension of every state/registry tensor is sharded over the `shard` mesh
axis, events are routed to shards by interned device index (exactly the
device-token record-key partitioning the reference uses), and the only
cross-shard traffic is psum'd stats riding ICI — replacing the reference's
gRPC fan-out + broker round-trips between stages.
"""

from sitewhere_tpu.parallel.mesh import make_mesh, shard_axis_size
from sitewhere_tpu.parallel.router import ShardRouter
from sitewhere_tpu.parallel.engine import ShardedPipelineEngine

__all__ = ["make_mesh", "shard_axis_size", "ShardRouter", "ShardedPipelineEngine"]
