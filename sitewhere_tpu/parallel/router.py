"""Host-side shard routing: global device index -> (shard, local index).

The reference routes events to microservice replicas by Kafka record key
(device token) -> partition -> consumer (SURVEY.md §2.5 row 1). Here the
same per-device affinity maps global interned index d to shard `d % S` with
local row `d // S`; each shard's state tensors are indexed by local rows, so
a shard only ever touches its own devices and the fused step needs NO
cross-shard communication for state updates — only stat reductions.

`route_columns` turns flat event columns into [S, B_local] stacked columns
(the layout shard_map splits along the mesh axis).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.ops.pack import EventBatch, _BASE_LANES

# the packed 3-row wire embeds its ts base in 11 row-0 lanes PER SHARD:
# routed layouts need at least that per-shard width (ops/pack.py)
_ROUTABLE_PACKED_MIN = _BASE_LANES


_I32_COLS = ("device_idx", "tenant_idx", "event_type", "ts", "mm_idx",
             "alert_type_idx", "alert_level")
_F32_COLS = ("value", "lat", "lon", "elevation")


@dataclass
class RoutedBatches:
    batch: EventBatch                    # columns shaped [S, B_local]
    overflow: Optional[EventBatch]       # flat batch of events beyond per-shard
    #                                      capacity (global indices, no padding)
    #                                      — callers requeue these next round

    @property
    def overflow_count(self) -> int:
        return 0 if self.overflow is None else int(self.overflow.valid.sum())


def concat_flat_batches(batches: List[EventBatch]) -> EventBatch:
    """Concatenate flat (1-D column) batches, keeping only valid rows.
    Host-side only: the result length is variable; route_columns repacks to
    fixed shapes."""
    keeps = [np.asarray(b.valid) for b in batches]
    cols = {}
    for name in _I32_COLS + _F32_COLS:
        cols[name] = np.concatenate(
            [np.asarray(getattr(b, name))[k] for b, k in zip(batches, keeps)])
    n = len(cols["device_idx"])
    return EventBatch(valid=np.ones(n, bool), **cols)


class FlatBatchArena:
    """Reusable flat-column staging for the overflow-requeue merge.

    The sharded submit path used to pay `concat_flat_batches` — 12 fresh
    per-column allocations — on EVERY step that carried a requeued
    overflow tail. This arena keeps one set of flat column buffers
    (grown geometrically, never shrunk) and writes the merged valid rows
    into them in place; `concat` returns an EventBatch of views into the
    arena, valid until the next `concat` on the same arena. Callers that
    need rows to outlive the next merge must copy them out (fancy-index
    slices of the views already do)."""

    def __init__(self):
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._ones: Optional[np.ndarray] = None
        self._cap = 0

    def _ensure(self, n: int) -> None:
        if n <= self._cap:
            return
        cap = max(n, 2 * self._cap, 1024)
        self._cols = {name: np.empty(cap, np.int32) for name in _I32_COLS}
        self._cols.update(
            {name: np.empty(cap, np.float32) for name in _F32_COLS})
        self._ones = np.ones(cap, bool)
        self._cap = cap

    def concat(self, batches: List[EventBatch]) -> EventBatch:
        """Valid rows of `batches`, in order, as views into the arena."""
        keeps = []
        n = 0
        for b in batches:
            valid = np.asarray(b.valid)
            rows = None if valid.all() else np.nonzero(valid)[0]
            k = valid.shape[0] if rows is None else len(rows)
            keeps.append((rows, k))
            n += k
        self._ensure(n)
        for name in _I32_COLS + _F32_COLS:
            dst = self._cols[name]
            pos = 0
            for b, (rows, k) in zip(batches, keeps):
                col = np.asarray(getattr(b, name))
                if rows is None:
                    dst[pos:pos + k] = col
                elif col.dtype == dst.dtype:
                    np.take(col, rows, out=dst[pos:pos + k])
                else:  # odd caller-supplied dtype: cast through a gather
                    dst[pos:pos + k] = col[rows]
                pos += k
        return EventBatch(
            valid=self._ones[:n],
            **{name: self._cols[name][:n] for name in _I32_COLS + _F32_COLS})


class ShardRouter:
    def __init__(self, n_shards: int, per_shard_batch: int,
                 staging_ring: int = 0):
        self.n_shards = n_shards
        self.per_shard_batch = per_shard_batch
        # Reusable routed-blob staging buffers: allocating + zeroing a fresh
        # [S, WIRE_ROWS, B] array per step (2.6 MB at production shapes —
        # mmap-backed, so every step paid page faults) was a visible slice
        # of the router's 2.26 ms/step. Buffers are LOANED, not rotated
        # blindly: route_batch hands each returned blob out on loan and
        # only recycles it once the borrower releases it (RoutedBlobView
        # release on GC, or explicit release_staging_buffer) — a caller
        # that holds a routed view arbitrarily long can never see its data
        # overwritten. The pool is bounded by `staging_ring`; when every
        # buffer is on loan a fresh one is allocated (never blocks).
        #
        # Default 0 (reuse OFF): on the cpu backend jax zero-copies
        # aligned numpy arrays into device buffers, so a recycled slot
        # could corrupt an in-flight step's input. Engines opt in only on
        # accelerator meshes, where device memory is separate and the H2D
        # copy is real (parallel/engine.py).
        self.staging_ring = staging_ring
        # Free-list model (no allocation bookkeeping): _staging_buffer
        # pops a FREE buffer of the right variant or allocates a fresh
        # one; release_ appends to the free lists under ONE shared bound
        # of `staging_ring` buffers across BOTH variants (5-row full /
        # 4-row compact), preferring the variant just used — alternating
        # traffic cannot double the pooled memory, and buffers never
        # returned (error paths) are simply garbage-collected. Entries
        # are (buffer, guard) pairs, FIFO.
        self._pools: Dict[int, List[tuple]] = {}
        self._pool_lock = None
        # multi-host lockstep pins the wire variant (see route_batch)
        self.fixed_wire_rows: Optional[int] = None
        # Column-routing arenas (route_columns): a ring of 2 preallocated
        # [S, B] column sets reused across steps, plus flat gather
        # scratch. A fresh 12-column zero allocation per step was most of
        # the column router's time at production shapes (mmap-backed ->
        # page faults); the ring of 2 keeps the PREVIOUS returned batch
        # intact while the next one routes (callers that hold a routed
        # batch across 2+ route_columns calls must copy it out).
        self._col_arenas: Optional[List[Dict[str, np.ndarray]]] = None
        self._col_arena_pos = 0
        self._scratch_i: Optional[np.ndarray] = None
        self._scratch_f: Optional[np.ndarray] = None

    def _buf_key(self, buf: np.ndarray):
        """Pool key of a loaned buffer: wire-rows count for routed
        [S, rows, B] blobs, ("flat", rows) for unrouted [rows, S*B]
        flat blobs (the device-routing feeder's staging format)."""
        if (buf.ndim == 3 and buf.shape[0] == self.n_shards
                and buf.shape[2] == self.per_shard_batch):
            return buf.shape[1]
        if (buf.ndim == 2
                and buf.shape[1] == self.n_shards * self.per_shard_batch):
            return ("flat", buf.shape[0])
        return None

    def _free_count(self) -> int:
        return sum(len(p) for p in self._pools.values())

    def _staging_buffer(self, rows: int) -> Optional[np.ndarray]:
        return self._pool_get(
            rows, (self.n_shards, rows, self.per_shard_batch))

    def flat_staging_buffer(self, rows: int) -> Optional[np.ndarray]:
        """Pooled UNROUTED flat staging blob [rows, S*B] for the
        device-routing path (same loan/guard/bound contract as the
        routed buffers; release through release_staging_buffer)."""
        return self._pool_get(
            ("flat", rows), (rows, self.n_shards * self.per_shard_batch))

    def _pool_get(self, key, shape) -> Optional[np.ndarray]:
        import threading

        if self.staging_ring <= 0:
            return None
        if self._pool_lock is None:
            self._pool_lock = threading.Lock()
        with self._pool_lock:
            pool = self._pools.setdefault(key, [])
            if not pool:
                return np.empty(shape, np.int32)
            buf, guard = pool.pop(0)
        if guard is not None:
            # device_put's H2D DMA may still be reading the host buffer
            # (PJRT immutable-until-transfer-completes): repacking before
            # the transfer finishes would corrupt the in-flight step's
            # input. The guard is a device array that becomes ready no
            # earlier than the transfer (the consuming step's output, or
            # the transferred array itself); by the time a buffer cycles
            # back around this is almost always already ready.
            try:
                guard.block_until_ready()
            except Exception:
                pass  # a failed step still implies the transfer finished
        return buf

    def release_staging_buffer(self, buf: np.ndarray, guard=None) -> None:
        """Return a loaned routed blob to the free pool. ONE bound across
        variants: when full, a free buffer of the OTHER variant is evicted
        in favor of this one (traffic switched variants); otherwise the
        extra simply drops to the garbage collector.

        `guard`: optional device array whose readiness proves the blob's
        H2D transfer completed (see _staging_buffer) — pass the consuming
        step's output when the blob was device_put."""
        if self.staging_ring <= 0 or self._pool_lock is None:
            return
        key = self._buf_key(buf)
        if key is None:
            return
        with self._pool_lock:
            if self._free_count() >= self.staging_ring:
                other = next(
                    (pool for variant, pool in self._pools.items()
                     if variant != key and pool), None)
                if other is None:
                    return  # bound reached by this variant: drop
                other.pop(0)  # evict a stale variant, keep the active one
            self._pools.setdefault(key, []).append((buf, guard))

    def discard_staging_buffer(self, buf: np.ndarray) -> None:
        """Error-path drop of a loaned blob whose transfer state is
        unknown (e.g. a step dispatch failed mid-flight): simply do not
        pool it — a later route allocates fresh; nothing to untrack."""
        return

    def route_batch(self, batch: EventBatch
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fused pack+route: flat EventBatch columns -> ([S, WIRE_ROWS, B]
        routed staging blob, overflow flat-row indices) in one native pass
        (swt_pack_route_blob) into a pooled staging buffer — replaces
        batch_to_blob + route_blob back to back (two full passes plus a
        zeroed intermediate). The returned blob is on loan when pooling is
        enabled; give it back via release_staging_buffer once done (the
        sharded engine wires this to RoutedBlobView's lifetime). Falls
        back to exactly the two-pass path when the native runtime is
        unavailable."""
        from sitewhere_tpu import native
        from sitewhere_tpu.ops.pack import (
            WIRE_ROWS_PACKED, batch_to_blob, wire_variant_for)

        if native.available():
            # Wire variant: per-batch packed/compact decision — EXCEPT
            # when pinned (fixed_wire_rows). Multi-host lockstep requires
            # every host to launch the same-shaped collective program per
            # tick; a host-local rows choice would desync the cluster, so
            # the sharded engine pins the full layout under
            # is_multiprocess.
            if self.fixed_wire_rows is not None:
                rows, ts_base = self.fixed_wire_rows, 0
            else:
                rows, ts_base = wire_variant_for(batch)
                rows, ts_base = self._routable_variant(rows, ts_base)
            out = self._staging_buffer(rows)
            res = native.pack_route_blob(batch, self.n_shards,
                                         self.per_shard_batch, out=out,
                                         wire_rows=rows, ts_base=ts_base)
            if res is not None:
                return res
            # device_idx out of wire range: the buffer never reached jax,
            # so hand it straight back, then let the numpy pack raise the
            # single shared diagnostic with min/max detail
            if out is not None:
                self.release_staging_buffer(out)
            batch_to_blob(batch)
            raise AssertionError("unreachable: numpy pack must have raised")
        # the lockstep pin applies on the numpy fallback too: pack
        # directly at the pinned layout (a packed 3-row blob is not a
        # zero-padded prefix of the classic one, so padding cannot widen)
        blob = batch_to_blob(batch, wire_rows=self.fixed_wire_rows)
        if blob.shape[0] == WIRE_ROWS_PACKED \
                and self.per_shard_batch < _ROUTABLE_PACKED_MIN:
            # per-shard rows cannot carry the lane-embedded ts base:
            # re-pack classic (tiny-shard test rigs only)
            from sitewhere_tpu.ops.pack import WIRE_ROWS_COMPACT

            blob = batch_to_blob(batch, wire_rows=WIRE_ROWS_COMPACT)
        return self.route_blob(blob)

    def _routable_variant(self, rows: int, ts_base: int):
        """Downgrade the packed variant when the PER-SHARD width cannot
        hold the lane-embedded ts base (11 lanes) — wire_variant_for
        checks the flat batch width, but routed row 0 is per shard."""
        from sitewhere_tpu.ops.pack import (
            WIRE_ROWS_COMPACT, WIRE_ROWS_PACKED)

        if rows == WIRE_ROWS_PACKED \
                and self.per_shard_batch < _ROUTABLE_PACKED_MIN:
            return WIRE_ROWS_COMPACT, 0
        return rows, ts_base

    def global_to_local(self, device_idx: np.ndarray
                        ) -> Tuple[np.ndarray, np.ndarray]:
        return device_idx % self.n_shards, device_idx // self.n_shards

    def local_to_global(self, shard: int, local_idx: np.ndarray) -> np.ndarray:
        return local_idx * self.n_shards + shard

    def shard_param(self, arr: np.ndarray) -> np.ndarray:
        """Re-lay a device-indexed [D, ...] array into [S, D//S, ...] so that
        row (s, l) holds global row l*S + s. D must be divisible by S."""
        D = arr.shape[0]
        S = self.n_shards
        if D % S:
            raise ValueError(f"device capacity {D} not divisible by {S} shards")
        return np.ascontiguousarray(
            arr.reshape((D // S, S) + arr.shape[1:]).swapaxes(0, 1))

    def unshard_param(self, arr: np.ndarray) -> np.ndarray:
        """Inverse of shard_param: [S, D//S, ...] -> [D, ...]."""
        S, L = arr.shape[0], arr.shape[1]
        return np.ascontiguousarray(
            np.asarray(arr).swapaxes(0, 1).reshape((S * L,) + arr.shape[2:]))

    def route_blob(self, blob: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Route a flat wire blob [WIRE_ROWS, n] into ([S, WIRE_ROWS, B]
        routed blob, overflow flat-row indices). The native single-pass
        router (host_runtime.cc swt_route_blob) replaces argsort +
        per-column scatters; the numpy fallback routes the blob rows the
        same way route_columns routes the 12 column arrays."""
        from sitewhere_tpu import native
        from sitewhere_tpu.ops.pack import (
            WIRE_DEV_MAX, WIRE_ROWS_PACKED, _BASE_SHIFT, _VALID_SHIFT,
            _embed_ts_base, _extract_ts_base_np)

        S, B = self.n_shards, self.per_shard_batch
        if np.asarray(blob).shape[-2] == WIRE_ROWS_PACKED \
                and B < _ROUTABLE_PACKED_MIN:
            raise ValueError(
                f"packed 3-row blobs need a per-shard width of at least "
                f"{_ROUTABLE_PACKED_MIN} lanes to carry the lane-embedded "
                f"ts base (per_shard_batch={B}); route a classic-layout "
                f"blob instead")
        if native.available():
            return native.route_blob(blob, S, B)
        blob = np.asarray(blob, np.int32)
        wire_rows, n = blob.shape
        head = blob[0]
        # packed blobs carry the ts base by LANE POSITION in row 0's spare
        # bits: lift it before scattering, strip the spare bits from every
        # routed head (zero on 4/5-row blobs), re-embed per shard after
        packed = wire_rows == WIRE_ROWS_PACKED
        base = int(_extract_ts_base_np(head)) if packed else 0
        valid = (head & (1 << _VALID_SHIFT)) != 0
        rows = None if valid.all() else np.nonzero(valid)[0]
        dev = (head if rows is None else head[rows]) & (WIRE_DEV_MAX - 1)
        ksorted, kept, over_rows = self._shard_sort(dev, rows)
        kstarts = np.zeros(S + 1, np.int64)
        np.cumsum(kept, out=kstarts[1:])
        # pooled staging buffer when enabled (the loaned-blob contract of
        # route_batch); tails past each shard's kept count are zeroed by
        # the per-shard placement, so no pre-zeroing is needed
        out = self._staging_buffer(wire_rows)
        if out is None:
            out = np.empty((S, wire_rows, B), np.int32)
        ghead = head[ksorted]
        gdev = ghead & (WIRE_DEV_MAX - 1)
        spare_clear = np.int32((1 << _BASE_SHIFT) - 1)
        ghead = (ghead & ~np.int32(WIRE_DEV_MAX - 1)
                 & spare_clear) | (gdev // S)
        self._place_sorted(out[:, 0, :], ghead, kept, kstarts)
        for r in range(1, wire_rows):
            self._place_sorted(out[:, r, :], blob[r][ksorted], kept, kstarts)
        if packed:
            _embed_ts_base(out[:, 0, :], base)
        return out, over_rows  # overflow in arrival order, like the native

    # -- shared shard-bucketing core (route_blob fallback + route_columns) --

    def _shard_sort(self, dev: np.ndarray, rows: Optional[np.ndarray]
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Stable shard bucketing of the valid flat rows.

        `dev` holds the (global) device index of each valid row; `rows`
        maps them back to flat batch positions (None = all rows valid, in
        place). Returns (ksorted, kept, over_rows): `ksorted` indexes the
        flat batch in shard-major arrival order truncated to per-shard
        capacity, `kept[s]` is the row count shard s keeps, `over_rows`
        are the flat indices of capacity overflow in arrival order.

        The stable argsort runs on the narrowest dtype the shard count
        fits (a uint8 radix sort is ~5x faster than int64 at 64k rows),
        and the no-overflow fast path skips the per-row position
        arithmetic entirely — the common production case."""
        S, B = self.n_shards, self.per_shard_batch
        shard = dev % S
        if S <= (1 << 8):
            shard = shard.astype(np.uint8)
        elif S <= (1 << 16):
            shard = shard.astype(np.uint16)
        order = np.argsort(shard, kind="stable")
        counts = np.bincount(shard, minlength=S).astype(np.int64)
        kept = np.minimum(counts, B)
        base = order if rows is None else rows[order]
        if int(counts.max(initial=0)) <= B:
            return base, kept, np.empty(0, np.int64)
        starts = np.zeros(S + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        pos = (np.arange(len(order), dtype=np.int64)
               - np.repeat(starts[:-1], counts))
        keep = pos < B
        return base[keep], kept, np.sort(base[~keep])

    @staticmethod
    def _place_sorted(dst: np.ndarray, gathered: np.ndarray,
                      kept: np.ndarray, kstarts: np.ndarray) -> None:
        """Fill [S, B] `dst` from shard-major-sorted `gathered` rows: one
        contiguous copy per shard plus a zeroed tail — replaces the fancy
        2-D scatter (and the full pre-zeroing) of the old router."""
        for s in range(dst.shape[0]):
            c = kept[s]
            row = dst[s]
            row[:c] = gathered[kstarts[s]:kstarts[s] + c]
            row[c:] = 0

    def _next_column_arena(self) -> Dict[str, np.ndarray]:
        if self._col_arenas is None:
            S, B = self.n_shards, self.per_shard_batch

            def alloc():
                cols = {name: np.empty((S, B), np.int32)
                        for name in _I32_COLS}
                cols.update({name: np.empty((S, B), np.float32)
                             for name in _F32_COLS})
                cols["valid"] = np.empty((S, B), bool)
                return cols

            self._col_arenas = [alloc(), alloc()]
        arena = self._col_arenas[self._col_arena_pos]
        self._col_arena_pos = (self._col_arena_pos + 1) % len(self._col_arenas)
        return arena

    def _gather_scratch(self, n: int) -> Tuple[np.ndarray, np.ndarray]:
        if self._scratch_i is None or self._scratch_i.shape[0] < n:
            cap = max(n, 4096)
            self._scratch_i = np.empty(cap, np.int32)
            self._scratch_f = np.empty(cap, np.float32)
        return self._scratch_i, self._scratch_f

    def route_columns(self, batch: EventBatch) -> RoutedBatches:
        """Scatter a flat host batch into per-shard sub-batches with local
        device indices — one stable bucketing pass, then one contiguous
        per-shard copy per column into a REUSED arena (no per-step
        per-column allocations; see _next_column_arena — the returned
        batch stays intact until the second-next route_columns on this
        router; copy it out to hold it longer). Arrival order per device
        is preserved. Rows beyond a shard's fixed capacity come back as
        `overflow` (flat, global indices, arrival order — matching the
        blob router) for the caller to requeue; fixed shapes are
        non-negotiable under jit."""
        S, B = self.n_shards, self.per_shard_batch
        valid = np.asarray(batch.valid)
        if valid.all():
            rows = None
            dev = np.asarray(batch.device_idx)
        else:
            rows = np.nonzero(valid)[0]
            dev = np.asarray(batch.device_idx)[rows]
        ksorted, kept, over_rows = self._shard_sort(dev, rows)
        k = len(ksorted)
        kstarts = np.zeros(S + 1, np.int64)
        np.cumsum(kept, out=kstarts[1:])
        arena = self._next_column_arena()
        scratch_i, scratch_f = self._gather_scratch(k)

        def gathered(name: str, scratch: np.ndarray) -> np.ndarray:
            col = np.asarray(getattr(batch, name))
            if col.dtype == scratch.dtype:
                return np.take(col, ksorted, out=scratch[:k])
            return col[ksorted]  # odd caller-supplied dtype: plain gather

        gdev = gathered("device_idx", scratch_i)
        np.floor_divide(gdev, S, out=gdev)          # global -> local rows
        self._place_sorted(arena["device_idx"], gdev, kept, kstarts)
        for name in _I32_COLS[1:]:
            self._place_sorted(arena[name], gathered(name, scratch_i),
                               kept, kstarts)
        for name in _F32_COLS:
            self._place_sorted(arena[name], gathered(name, scratch_f),
                               kept, kstarts)
        out_valid = arena["valid"]
        for s in range(S):
            out_valid[s, :kept[s]] = True
            out_valid[s, kept[s]:] = False
        routed = EventBatch(**arena)

        overflow = None
        if len(over_rows):
            ocols = {name: np.asarray(getattr(batch, name))[over_rows]
                     for name in _I32_COLS + _F32_COLS}
            overflow = EventBatch(valid=np.ones(len(over_rows), bool),
                                  **ocols)
        return RoutedBatches(batch=routed, overflow=overflow)
