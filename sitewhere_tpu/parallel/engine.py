"""ShardedPipelineEngine: the fused step over a device mesh.

Scaling story (SURVEY.md §2.5): the reference adds replicas per microservice
and lets Kafka split partitions; here ONE SPMD program runs on every chip.
Each shard owns devices `d % S == s` (their state rows, their slice of the
registry mirror); events reach their owner shard either via the on-device
routing prologue (ops/route.py — bucketing + one all_to_all fused into the
step; default on multi-shard single-controller meshes) or the host arena
router (single-chip, multi-host, and skew spills); rule tables and zone
geometry are replicated (small, read-only). Cross-shard communication is
one row exchange (device routing) plus the psum of per-batch stats over
ICI per step, vs. the reference's per-event gRPC fan-out.

Multi-host note: the same program runs under `jax.distributed` across hosts —
the mesh spans all processes' devices and each host routes/feeds the
sub-batches of its local shards (the standard multi-host jax data-loading
contract). ICI carries the psum; DCN only carries control-plane traffic.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from sitewhere_tpu.model import DeviceAlert
from sitewhere_tpu.ops.pack import EventBatch, blob_to_batch
from sitewhere_tpu.runtime.bus import jittered
from sitewhere_tpu.runtime.faults import fault_point
from sitewhere_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_axis_size
from sitewhere_tpu.parallel.router import ShardRouter
from sitewhere_tpu.pipeline.engine import PipelineEngine
from sitewhere_tpu.pipeline.state_tensors import (
    DeviceStateTensors, init_device_state_np)
from sitewhere_tpu.pipeline.step import PipelineParams, ProcessOutputs, process_batch
from sitewhere_tpu.registry.tensors import RegistryTensors


def _tree_specs(tree, spec):
    return jax.tree_util.tree_map(lambda _: spec, tree)


def _put_global(value: np.ndarray, sharding: NamedSharding):
    """Place a host value onto a (possibly multi-host) sharding using ONLY
    local single-device transfers.

    jax 0.9's device_put supports cross-host placements by lowering them
    to COLLECTIVE transfers — under multi-controller that would have to
    run in lockstep on every process, but params/state refreshes fire at
    different ticks per host (registry versions bump independently), which
    desyncs the collective order and aborts the whole cluster (observed:
    gloo 'Received data size doesn't match expected size'). Every process
    holds the full host value here, so per-device local placement is
    always possible and never communicates."""
    value = np.asarray(value)
    shards = [
        jax.device_put(value[index], device)
        for device, index in sharding.addressable_devices_indices_map(
            value.shape).items()]
    return jax.make_array_from_single_device_arrays(
        value.shape, sharding, shards)


def _put_global_tree(tree, sharding_tree):
    return jax.tree_util.tree_map(
        lambda value, sharding: _put_global(value, sharding),
        tree, sharding_tree)


class RoutedBlobView:
    """Lazy routed-batch handle returned by ShardedPipelineEngine.submit:
    the staged wire blob IS the data; EventBatch columns unpack on first
    access (only alert materialization needs them, and only for steps
    that fired). Column attributes proxy to the unpacked batch, so code
    that treats the handle as an EventBatch keeps working.

    `shard_ids` maps the blob's leading axis to GLOBAL shard indices —
    under multi-process feeding the view holds only this process's local
    shard blocks.

    When the blob is a pooled staging buffer on loan from the router,
    `release` returns it for reuse once this view is garbage-collected —
    holding a view arbitrarily long is always safe (the buffer cannot be
    recycled underneath it). Recycling is additionally guarded against
    in-flight async H2D DMA: the release carries the consuming step's
    output as a transfer-completion guard that the pool blocks on before
    handing the buffer out again (router.release_staging_buffer). The cpu
    backend, where jax may zero-copy host buffers outright, never loans
    buffers (staging_ring=0)."""

    __slots__ = ("blob", "shard_ids", "_batch", "_release", "__weakref__")

    def __init__(self, blob: np.ndarray,
                 shard_ids: Optional[List[int]] = None,
                 release: Optional[Callable[[], None]] = None):
        self.blob = blob
        self.shard_ids = shard_ids
        self._batch = None
        self._release = release

    @property
    def batch(self) -> EventBatch:
        if self._batch is None:
            from sitewhere_tpu.ops.pack import blob_to_batch_np

            self._batch = blob_to_batch_np(self.blob)
        return self._batch

    def __getattr__(self, name):
        return getattr(self.batch, name)

    def __del__(self):
        release, self._release = self._release, None
        if release is not None:
            try:
                release()
            except Exception:
                pass


class DeviceRoutedView:
    """Lazy materialization handle for a DEVICE-routed step: the host
    never builds the routed [S, B] layout — the mesh does (ops/route.py)
    — so this view reconstructs only what alert materialization needs
    (the routed device_idx/ts columns), only when something actually
    fired, from the flat wire blob it keeps. The reconstruction is the
    same stable `_shard_sort` bucketing the host router uses, over TWO
    columns instead of a 5-row blob scatter — and it runs on the cold
    path, not per step.

    When the flat blob is a pooled loan (router.flat_staging_buffer),
    `release` hands it back on GC exactly like RoutedBlobView."""

    __slots__ = ("blob", "shard_ids", "_router", "_cols", "_flat",
                 "_full", "_release", "__weakref__")

    def __init__(self, blob: np.ndarray, router: ShardRouter,
                 release: Optional[Callable[[], None]] = None):
        self.blob = blob                 # flat [wire_rows, S*B]
        self.shard_ids = None
        self._router = router
        self._cols = None
        self._flat = None
        self._full = None
        self._release = release

    def _flat_batch(self) -> EventBatch:
        if self._flat is None:
            from sitewhere_tpu.ops.pack import blob_to_batch_np

            self._flat = blob_to_batch_np(self.blob)
        return self._flat

    def _sort(self):
        flat = self._flat_batch()
        rt = self._router
        valid = np.asarray(flat.valid)
        rows = None if valid.all() else np.nonzero(valid)[0]
        devcol = np.asarray(flat.device_idx)
        dev = devcol if rows is None else devcol[rows]
        ksorted, kept, _ = rt._shard_sort(dev, rows)
        kstarts = np.zeros(rt.n_shards + 1, np.int64)
        np.cumsum(kept, out=kstarts[1:])
        return ksorted, kept, kstarts

    def _routed_cols(self):
        if self._cols is None:
            flat = self._flat_batch()
            rt = self._router
            S, B = rt.n_shards, rt.per_shard_batch
            ksorted, kept, kstarts = self._sort()
            out_dev = np.zeros((S, B), np.int32)
            out_ts = np.zeros((S, B), np.int32)
            rt._place_sorted(out_dev,
                             np.asarray(flat.device_idx)[ksorted] // S,
                             kept, kstarts)
            rt._place_sorted(out_ts, np.asarray(flat.ts)[ksorted],
                             kept, kstarts)
            self._cols = (out_dev, out_ts)
        return self._cols

    @property
    def device_idx(self) -> np.ndarray:  # LOCAL indices, [S, B]
        return self._routed_cols()[0]

    @property
    def ts(self) -> np.ndarray:          # [S, B]
        return self._routed_cols()[1]

    @property
    def batch(self) -> EventBatch:
        """Full routed [S, B] EventBatch (wire-faithful: reconstructed
        from the flat blob, local device indices) — RoutedBlobView
        compat for oracle/differential consumers. Cold path only."""
        if self._full is None:
            import dataclasses as _dc

            flat = self._flat_batch()
            rt = self._router
            S, B = rt.n_shards, rt.per_shard_batch
            ksorted, kept, kstarts = self._sort()
            cols = {}
            for f in _dc.fields(flat):
                col = np.asarray(getattr(flat, f.name))
                gathered = col[ksorted]
                if f.name == "device_idx":
                    gathered = gathered // S   # global -> local rows
                out = np.zeros((S, B), col.dtype)
                rt._place_sorted(out, gathered, kept, kstarts)
                cols[f.name] = out
            self._full = EventBatch(**cols)
        return self._full

    def __getattr__(self, name):
        return getattr(self.batch, name)

    def __del__(self):
        release, self._release = self._release, None
        if release is not None:
            try:
                release()
            except Exception:
                pass


class _PreparedStep:
    """Host-side routing decision for one step, between _prepare_step and
    stage_prepared: `kind` is "host" (arena-routed [S, rows, B] blob,
    possibly a pooled loan) or "device" (unrouted flat [rows, S*B] blob;
    the mesh routes it in the step's prologue)."""

    __slots__ = ("kind", "blob", "flight")

    def __init__(self, kind: str, blob: np.ndarray, flight=None):
        self.kind = kind
        self.blob = blob
        # flight record opened by _prepare_step; rides the prepared ->
        # staged -> dispatched handoff so a pipelined feeder's stage-ahead
        # work lands on the SAME record its dispatch completes (explicit
        # cross-thread trace handoff)
        self.flight = flight


class _StagedStep:
    """In-flight staged blob between stage_prepared and dispatch_staged:
    the (possibly still transferring) global device array, the lazy
    materialization view, the host blob the events meter counts from,
    the loaned host blob to release after dispatch, and which compiled
    program ("host" routed / "device" routing-prologue) consumes it."""

    __slots__ = ("blob", "view", "counted", "routed_blob", "kind",
                 "flight", "slot")

    def __init__(self, blob, view, counted, routed_blob,
                 kind: str = "host", flight=None, slot=None):
        self.blob = blob
        self.view = view
        self.counted = counted
        self.routed_blob = routed_blob
        self.kind = kind
        self.flight = flight
        # staging-ring slot (pipeline/staging.py) the transfer occupies;
        # dispatch_staged releases it with the step output as guard.
        # None when the caller bypassed the ring (overflow drain blobs).
        self.slot = slot


class ShardedPipelineEngine(PipelineEngine):
    """Drop-in engine whose state/params/batches carry a leading shard axis.

    `per_shard_batch` is the per-chip batch; global throughput scales with the
    mesh. Device capacity must divide evenly by the mesh size.
    """

    def __init__(self, registry_tensors: RegistryTensors,
                 mesh: Optional[Mesh] = None, per_shard_batch: int = 4096,
                 device_routing: Optional[bool] = None,
                 **kwargs):
        self.mesh = mesh or make_mesh()
        self.n_shards = shard_axis_size(self.mesh)
        if registry_tensors.devices.capacity % self.n_shards:
            raise ValueError(
                f"max_devices {registry_tensors.devices.capacity} must be "
                f"divisible by {self.n_shards} shards")
        super().__init__(registry_tensors, batch_size=per_shard_batch, **kwargs)
        # staging-ring reuse only on accelerator meshes: the cpu backend
        # zero-copies aligned numpy arrays into device buffers, so a
        # recycled routed-blob slot could corrupt an in-flight step's
        # input (see PipelineEngine._staging_blob_buffer)
        ring = 0 if self._target_platform() == "cpu" else 4
        self.router = ShardRouter(self.n_shards, per_shard_batch,
                                  staging_ring=ring)
        if self.is_multiprocess:
            # lockstep invariant: every host must launch the SAME-shaped
            # collective program per tick; a per-host compact-vs-full wire
            # choice (driven by local batch content) would pair
            # differently-shaped collectives across hosts. Pin the full
            # layout cluster-wide.
            from sitewhere_tpu.ops.pack import WIRE_ROWS
            self.router.fixed_wire_rows = WIRE_ROWS
        # host packer accepts a full mesh's worth of events per flat batch
        from sitewhere_tpu.ops.pack import EventPacker
        self.packer = EventPacker(per_shard_batch * self.n_shards,
                                  registry_tensors.devices)
        # On-device shard routing (ops/route.py): the feeder ships the
        # UNROUTED flat blob (pack + one H2D) and a fused routing
        # prologue inside the step's shard_map buckets + all_to_all's
        # rows to their owner shards — no per-row host bucketing. Auto:
        # on for real multi-shard single-controller meshes; single-chip
        # "sharded" meshes keep the host path (nothing to exchange, and
        # the host-vs-device router micro-bench needs the host baseline);
        # multi-host clusters keep the host path (per-host feeding +
        # take_foreign owns cross-host rows there).
        if device_routing is None:
            device_routing = self.n_shards >= 2 and not self.is_multiprocess
        elif device_routing and self.is_multiprocess:
            raise ValueError(
                "device_routing is single-controller only: multi-host "
                "clusters feed per-host and forward foreign rows over "
                "the bus edge (take_foreign)")
        self.device_routing = bool(device_routing)
        from sitewhere_tpu.ops.route import route_lane_capacity
        self.route_lane_capacity = route_lane_capacity(
            per_shard_batch, self.n_shards)
        # loud accounting for the bounded host-spill fallback and the
        # (defensive, normally zero) on-device drop counter
        self.device_route_steps = 0
        self.device_route_fallbacks = 0
        self.device_route_dropped = 0
        self._sharded_step = None  # built lazily once specs are known
        self._sharded_step_device = None
        # shard-overflow events requeued ahead of the next submit; when the
        # backlog exceeds the bound, submit() drains it with extra steps
        # (backpressure) instead of dropping rows
        self._overflow: Optional[EventBatch] = None
        self.max_overflow_events = per_shard_batch * self.n_shards * 4
        # reusable flat staging for the overflow+batch merge: the requeue
        # path used to pay 12 fresh column allocations per carrying step
        from sitewhere_tpu.parallel.router import FlatBatchArena
        self._merge_arena = FlatBatchArena()
        self.total_dropped = 0  # kept for the stats contract; stays 0
        self.drain_steps = 0
        # alerts fired during drain steps, delivered on the next
        # materialize_alerts call (drain outputs never reach the caller);
        # bounded so a caller that never materializes can't leak memory —
        # overflow is counted on alerts_dropped like any bounded drop
        self._pending_alerts: List[DeviceAlert] = []
        self.max_pending_alerts = 65536

    def _target_platform(self) -> str:
        return self.mesh.devices.flat[0].platform

    # -- multi-process topology -------------------------------------------

    @property
    def local_shards(self) -> List[int]:
        """Global shard indices whose device lives in THIS process (mesh
        order). Single-process: all of them."""
        cached = getattr(self, "_local_shards", None)
        if cached is None:
            me = jax.process_index()
            cached = [i for i, d in enumerate(self.mesh.devices.flat)
                      if d.process_index == me]
            self._local_shards = cached
        return cached

    @property
    def is_multiprocess(self) -> bool:
        return len(self.local_shards) < self.n_shards

    def take_foreign(self) -> Optional[EventBatch]:
        """Events this host ingested whose owner shard lives on ANOTHER
        process. The multi-host data contract is per-host feeding: each
        host stages only its local shards' rows; rows owned elsewhere are
        handed back here for the caller to forward over the bus edge
        (keyed so the owning host's consumer picks them up) — never
        silently dropped. Returns a flat batch or None."""
        batch, self._foreign = getattr(self, "_foreign", None), None
        return batch

    # -- initialization -------------------------------------------------------

    def on_initialize(self, monitor) -> None:
        S = self.n_shards
        # Build the stacked initial state in host numpy and place it with ONE
        # device_put pinned to the mesh: no op may dispatch on the default
        # backend here — the mesh can be CPU devices inside a process whose
        # default backend is a TPU client that is broken or absent (the
        # driver's dryrun environment).
        local = init_device_state_np(
            self.registry.devices.capacity // S, self.measurement_slots,
            self.max_tenants)
        stacked = jax.tree_util.tree_map(
            lambda a: np.ascontiguousarray(
                np.broadcast_to(a, (S,) + a.shape)), local)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        self._state = _put_global_tree(stacked, _tree_specs(stacked, shard0))
        if self._rule_state is None:
            self._rule_state = self._init_rule_state()
        if self._model_state is None:
            self._model_state = self._init_model_state()
        if self._actuation_state is None:
            self._actuation_state = self._init_actuation_state()
        self._refresh_params()
        self._build_step()

    def _init_rule_state(self):
        # rule-program state rides the shard axis with the other state
        # tensors: per-shard [S, D/S, P, 4*slots+2] fused slab lanes plus
        # per-shard [S, P] generation/counter rows (counters are additive
        # partials, summed on read like the tenant counters). Sized by
        # _rule_state_dims: a [.., 1, 1] placeholder while no programs
        # are installed (the stage is dropped at trace time).
        from sitewhere_tpu.ops.stateful import init_rule_state_np

        dims = self._rule_state_dims()
        self._rule_state_built_dims = dims
        S = self.n_shards
        local = init_rule_state_np(
            self.registry.devices.capacity // S, *dims)
        stacked = jax.tree_util.tree_map(
            lambda a: np.ascontiguousarray(
                np.broadcast_to(a, (S,) + a.shape)), local)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        return _put_global_tree(stacked, _tree_specs(stacked, shard0))

    def _init_model_state(self):
        # anomaly-model state rides the shard axis exactly like the
        # rule-program state: per-shard [S, D/S, P, 4*F+2] fused slab
        # lanes plus
        # per-shard [S, P] generation/counter rows (fire/eval counters
        # are additive partials, summed on read). Sized by
        # _model_state_dims: a [.., 1, 1] placeholder while no models
        # are installed (the stage is dropped at trace time).
        from sitewhere_tpu.ops.anomaly import init_model_state_np

        dims = self._model_state_dims()
        self._model_state_built_dims = dims
        S = self.n_shards
        local = init_model_state_np(
            self.registry.devices.capacity // S, *dims)
        stacked = jax.tree_util.tree_map(
            lambda a: np.ascontiguousarray(
                np.broadcast_to(a, (S,) + a.shape)), local)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        return _put_global_tree(stacked, _tree_specs(stacked, shard0))

    def _init_actuation_state(self):
        # actuation debounce state rides the shard axis exactly like the
        # model state: per-shard [S, D/S, P, 6] fused slab lanes plus
        # per-shard [S, P] generation/counter rows (fire/debounce counters
        # are additive partials, summed on read). Sized by
        # _actuation_state_dims: a [.., 1, ..] placeholder while no
        # policies are installed (the stage is dropped at trace time).
        from sitewhere_tpu.ops.actuate import init_actuation_state_np

        dims = self._actuation_state_dims()
        self._actuation_state_built_dims = dims
        S = self.n_shards
        local = init_actuation_state_np(
            self.registry.devices.capacity // S, *dims)
        stacked = jax.tree_util.tree_map(
            lambda a: np.ascontiguousarray(
                np.broadcast_to(a, (S,) + a.shape)), local)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        return _put_global_tree(stacked, _tree_specs(stacked, shard0))

    def _build_step_blob(self) -> None:
        # the single-chip jit is never used by the sharded engine; the
        # collective program is built by _build_step instead
        self._step_blob = None
        self._step_built_config = self._step_static_config()

    def _ensure_step_current(self) -> None:
        if (self._sharded_step is not None
                and getattr(self, "_sharded_built_config", None)
                != self._step_static_config()):
            self._ensure_rule_state_sized()
            self._ensure_model_state_sized()
            self._ensure_actuation_state_sized()
            self._build_step()

    def _build_step(self) -> None:
        params_template = self._params
        dev = P(SHARD_AXIS)
        rep = P()
        params_specs = PipelineParams(
            assignment_status=dev, tenant_idx=dev, area_idx=dev,
            device_type_idx=dev,
            threshold=_tree_specs(params_template.threshold, rep),
            zones=_tree_specs(params_template.zones, rep),
            geofence=_tree_specs(params_template.geofence, rep),
            programs=_tree_specs(params_template.programs, rep),
            # model weight tables replicate like the rule tables (small,
            # read-only); only the feature STATE rides the shard axis
            models=_tree_specs(params_template.models, rep),
            # policy tables replicate too; the debounce STATE is sharded
            policies=_tree_specs(params_template.policies, rep))
        state_specs = _tree_specs(self._state, dev)
        rule_state_specs = _tree_specs(self._rule_state, dev)
        model_state_specs = _tree_specs(self._model_state, dev)
        actuation_state_specs = _tree_specs(self._actuation_state, dev)
        blob_specs = dev  # [S, WIRE_ROWS, B] single staging blob, sharded on S
        out_specs = ProcessOutputs(
            valid=dev, unregistered=dev, threshold_fired=dev,
            threshold_first_rule=dev, threshold_alert_level=dev,
            geofence_fired=dev, geofence_first_rule=dev,
            geofence_alert_level=dev, program_fired=dev,
            program_first_rule=dev, program_alert_level=dev,
            model_fired=dev, model_first=dev, model_level=dev,
            model_score=dev,
            tenant_counts=rep, processed=rep,
            alerts=rep,
            # per-shard compacted alert lanes ride the shard axis with
            # the other outputs — no extra collective, one host fetch
            alert_lanes=dev,
            # the command lane rides the same fetch, shard-major like the
            # alert lane
            command_lanes=dev)
        (programs_enabled, node_limit, models_enabled,
         actuation_enabled) = self._step_static_config()

        def sq(a):
            # shard_map hands blocks with the mapped axis kept (size 1); the
            # per-shard program works on squeezed local shapes.
            return a.reshape(a.shape[1:])

        def unsq(a):
            return a[None]

        def local_step(params, state, rule_state, model_state,
                       actuation_state, local_blob, route_dropped=None):
            """Shared per-shard body: fused step over an already-LOCAL
            [wire_rows, B] routed blob. `route_dropped` (device-routing
            prologue only) rides out on the alert lanes' spare counts
            slot — no extra output, no extra fetch."""
            params = params.replace(
                assignment_status=sq(params.assignment_status),
                tenant_idx=sq(params.tenant_idx),
                area_idx=sq(params.area_idx),
                device_type_idx=sq(params.device_type_idx))
            state = jax.tree_util.tree_map(sq, state)
            rule_state = jax.tree_util.tree_map(sq, rule_state)
            model_state = jax.tree_util.tree_map(sq, model_state)
            actuation_state = jax.tree_util.tree_map(sq, actuation_state)
            batch = blob_to_batch(local_blob)        # [12, B] -> columns
            (new_state, new_rule_state, new_model_state,
             new_actuation_state, out) = process_batch(
                params, state, rule_state, model_state, actuation_state,
                batch,
                geofence_impl=self.geofence_impl,
                alert_lane_capacity=self.alert_lane_capacity,
                programs_enabled=programs_enabled,
                program_node_limit=node_limit,
                models_enabled=models_enabled,
                actuation_enabled=actuation_enabled,
                command_lane_capacity=self.command_lane_capacity)
            lanes = out.alert_lanes
            if route_dropped is not None:
                from sitewhere_tpu.ops.route import ROUTE_DROPPED_SLOT
                lanes = lanes.at[3, ROUTE_DROPPED_SLOT].set(route_dropped)
            new_state = jax.tree_util.tree_map(unsq, new_state)
            new_rule_state = jax.tree_util.tree_map(unsq, new_rule_state)
            new_model_state = jax.tree_util.tree_map(unsq, new_model_state)
            new_actuation_state = jax.tree_util.tree_map(
                unsq, new_actuation_state)
            out = out.replace(
                valid=unsq(out.valid), unregistered=unsq(out.unregistered),
                threshold_fired=unsq(out.threshold_fired),
                threshold_first_rule=unsq(out.threshold_first_rule),
                threshold_alert_level=unsq(out.threshold_alert_level),
                geofence_fired=unsq(out.geofence_fired),
                geofence_first_rule=unsq(out.geofence_first_rule),
                geofence_alert_level=unsq(out.geofence_alert_level),
                program_fired=unsq(out.program_fired),
                program_first_rule=unsq(out.program_first_rule),
                program_alert_level=unsq(out.program_alert_level),
                model_fired=unsq(out.model_fired),
                model_first=unsq(out.model_first),
                model_level=unsq(out.model_level),
                model_score=unsq(out.model_score),
                alert_lanes=unsq(lanes),
                command_lanes=unsq(out.command_lanes),
                tenant_counts=jax.lax.psum(out.tenant_counts, SHARD_AXIS),
                processed=jax.lax.psum(out.processed, SHARD_AXIS),
                alerts=jax.lax.psum(out.alerts, SHARD_AXIS))
            return (new_state, new_rule_state, new_model_state,
                    new_actuation_state, out)

        def sharded(params, state, rule_state, model_state, actuation_state,
                    blob):
            return local_step(params, state, rule_state, model_state,
                              actuation_state, sq(blob))

        def build(fn, blob_spec):
            specs = dict(mesh=self.mesh,
                         in_specs=(params_specs, state_specs,
                                   rule_state_specs, model_state_specs,
                                   actuation_state_specs, blob_spec),
                         out_specs=(state_specs, rule_state_specs,
                                    model_state_specs,
                                    actuation_state_specs, out_specs))
            try:
                # the geofence containment scan's carry is replicated
                # only through the psum at the end of the step — a loop
                # invariant the replication checker cannot infer
                # statically (same workaround as
                # parallel/distributed.py's ring combine)
                mapped = _shard_map(fn, check_vma=False, **specs)
            except TypeError:  # older jax spells it check_rep
                mapped = _shard_map(fn, check_rep=False, **specs)
            return jax.jit(mapped, donate_argnums=(1, 2, 3, 4))

        self._sharded_step = build(sharded, blob_specs)
        if self.device_routing:
            from sitewhere_tpu.ops.route import device_route_chunk
            n_shards = self.n_shards
            per_shard = self.batch_size
            lane_cap = self.route_lane_capacity

            def sharded_device(params, state, rule_state, model_state,
                               actuation_state, flat_blob):
                # flat_blob block: [wire_rows, B] UNROUTED lane chunk
                # (the flat blob split along lanes, P(None, shard)) —
                # the routing prologue buckets + all_to_all's it to the
                # owner shards inside the same program as the step
                local_blob, dropped = device_route_chunk(
                    flat_blob, n_shards, per_shard, lane_cap, SHARD_AXIS)
                return local_step(params, state, rule_state, model_state,
                                  actuation_state, local_blob,
                                  route_dropped=dropped)

            self._sharded_step_device = build(
                sharded_device, P(None, SHARD_AXIS))
        else:
            self._sharded_step_device = None
        self._sharded_built_config = (programs_enabled, node_limit,
                                      models_enabled, actuation_enabled)

    # -- params ---------------------------------------------------------------

    def _refresh_params(self) -> None:
        snap = self.registry.snapshot()
        threshold = self._compile_threshold_table()
        geofence = self._compile_geofence_table()
        programs = self._compile_program_table()
        models = self._compile_model_table()
        policies = self._compile_policy_table()
        from sitewhere_tpu.ops.geofence import ZoneTable
        zones = ZoneTable(vertices=snap.zone_vertices, nvert=snap.zone_nvert,
                          tenant_idx=snap.zone_tenant, active=snap.zone_active)
        router = getattr(self, "router", None) or ShardRouter(
            self.n_shards, self.batch_size)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        rep = NamedSharding(self.mesh, P())
        params = PipelineParams(
            assignment_status=router.shard_param(snap.assignment_status),
            tenant_idx=router.shard_param(snap.tenant_idx),
            area_idx=router.shard_param(snap.area_idx),
            device_type_idx=router.shard_param(snap.device_type_idx),
            threshold=threshold, zones=zones, geofence=geofence,
            programs=programs, models=models, policies=policies)
        shardings = PipelineParams(
            assignment_status=shard0, tenant_idx=shard0, area_idx=shard0,
            device_type_idx=shard0,
            threshold=_tree_specs(threshold, rep),
            zones=_tree_specs(zones, rep),
            geofence=_tree_specs(geofence, rep),
            programs=_tree_specs(programs, rep),
            models=_tree_specs(models, rep),
            policies=_tree_specs(policies, rep))
        self._params = _put_global_tree(params, shardings)
        self._params_built_for = (snap.version, self._rules_version)

    # -- processing -----------------------------------------------------------

    def submit(self, batch: EventBatch, age=None
               ) -> Tuple[EventBatch, ProcessOutputs]:
        """Route a flat host batch (global indices, any length) to shards and
        run one collective step. Returns (the LAST routed batch with a
        [S, B] layout, outputs of the last step). Events overflowing a
        shard's capacity are requeued ahead of the next submit
        (at-least-once; order per device preserved because overflow rows
        predate the next batch's rows).

        Backpressure instead of loss: when sustained skew piles overflow
        past `max_overflow_events`, submit runs extra drain steps (overflow
        only, no new events) until the backlog fits. The call gets slower —
        which is the signal the caller needs — and `total_dropped` stays 0;
        `drain_steps` counts the extra steps for observability."""
        params = self._ensure_params()
        batch = self.merge_pending_overflow(batch)
        # Device routing (default on real multi-shard meshes): pack the
        # flat blob, one H2D, and let the mesh route it inside the step
        # (ops/route.py). Host arena route (fused native pack+route into
        # a pooled routed blob) remains the fallback for skewed batches
        # that would overflow a device lane — and the only path on
        # single-chip meshes and multi-host clusters.
        prepared, over_rows = self._prepare_step(batch, age=age)
        try:
            routed_batch, outputs = self._one_step(params, prepared)
        except BaseException:
            if not self.is_multiprocess:
                # transfer state unknown mid-failure: drop the loaned
                # buffer from the pool instead of leaking it (or recycling
                # a possibly-in-DMA one). The multiprocess path already
                # released it before the step (it never reaches jax there
                # — only the local copy does), so discarding again would
                # under-count the pool.
                self.router.discard_staging_buffer(prepared.blob)
            raise
        self.park_overflow(batch, over_rows)
        # Multi-process lockstep: every host must launch the SAME number of
        # collective programs per submit — extra drain steps on one host
        # would pair its psums with a peer's NEXT step (undefined). The
        # cluster step loop applies backpressure instead (it stops pulling
        # new work while pending_overflow exceeds the bound, so the
        # backlog drains one lockstep tick at a time).
        while (not self.is_multiprocess
               and self._overflow is not None
               and int(self._overflow.valid.sum()) > self.max_overflow_events):
            # the caller only sees the LAST step; materialize the alerts of
            # the step that is about to be superseded so they aren't lost
            self._stash_pending_alerts(
                self._materialize_routed(routed_batch, outputs))
            backlog = self._overflow
            self._overflow = None
            self.drain_steps += 1
            self._metrics.counter("overflow.drain_steps").inc()
            prepared, over_rows = self._prepare_step(backlog)
            routed_batch, outputs = self._one_step(params, prepared)
            self.park_overflow(backlog, over_rows)
        return routed_batch, outputs

    def _prepare_step(self, batch: EventBatch, age=None
                      ) -> Tuple["_PreparedStep", np.ndarray]:
        """Host half of one step's routing decision. Device-routing mode:
        when the flat batch fits the mesh's fixed lanes (cheap bincount
        guard, ops/route.py), pack it UNROUTED — the mesh routes it — and
        no overflow is possible. Otherwise (skew past lane capacity, a
        merged backlog longer than the global batch, host-routing mode):
        the host arena route, whose overflow rows requeue as always —
        the bounded, loudly-counted spill path."""
        rec = self.flight.begin_step(engine=self.name)
        if age is not None:
            # ingest-age sidecar rides the flight record through the
            # stage_prepared/dispatch_staged handoffs (feeder threads);
            # _materialize_routed closes it (runtime/eventage.py)
            rec.age = age
        self._sample_tenant_mix(rec, batch)
        if self.device_routing and self._device_route_fits(batch):
            self.device_route_steps += 1
            self._metrics.counter("route.device_steps").inc()
            rec.begin_stage("route_device")
            fault_point("pack_fail")
            blob = self._pack_flat_blob(batch)
            rec.end_stage("route_device")
            self._stage_hist.observe(rec.stage_s("route_device"),
                                     engine=self.name, stage="route_device")
            return (_PreparedStep("device", blob, flight=rec),
                    np.empty(0, np.int64))
        if self.device_routing:
            self.device_route_fallbacks += 1
            self._metrics.counter("route.host_fallbacks").inc()
        rec.begin_stage("route_host")
        fault_point("pack_fail")
        routed_blob, over_rows = self.router.route_batch(batch)
        rec.end_stage("route_host")
        self._stage_hist.observe(rec.stage_s("route_host"),
                                 engine=self.name, stage="route_host")
        return _PreparedStep("host", routed_blob, flight=rec), over_rows

    def _device_route_fits(self, batch: EventBatch) -> bool:
        from sitewhere_tpu.ops.route import host_fits_device_route

        n = batch.device_idx.shape[0]
        if n > self.batch_size * self.n_shards:
            return False  # longer than the global batch: host path requeues
        return host_fits_device_route(
            batch.device_idx, batch.valid, self.n_shards, self.batch_size,
            self.route_lane_capacity)

    def _pack_flat_blob(self, batch: EventBatch) -> np.ndarray:
        """Pack a flat batch into the UNROUTED [wire_rows, S*B] staging
        blob (pooled when the mesh is an accelerator), zero-padding short
        batches to the global width. This—plus one device_put—is ALL the
        host does per step in device-routing mode."""
        from sitewhere_tpu.ops.pack import batch_to_blob, wire_variant_for

        G = self.batch_size * self.n_shards
        rows, ts_base = wire_variant_for(batch)
        rows, ts_base = self.router._routable_variant(rows, ts_base)
        buf = self.router.flat_staging_buffer(rows)
        n = batch.device_idx.shape[0]
        if n == G:
            return batch_to_blob(batch, out=buf, wire_rows=rows)
        small = batch_to_blob(batch, wire_rows=rows)
        if buf is None:
            buf = np.empty((small.shape[0], G), np.int32)
        buf[:, :n] = small
        buf[:, n:] = 0
        return buf

    @staticmethod
    def _slice_flat(batch: EventBatch,
                    rows: np.ndarray) -> Optional[EventBatch]:
        if len(rows) == 0:
            return None
        return jax.tree_util.tree_map(lambda a: np.asarray(a)[rows], batch)

    # -- overflow backlog (shared by submit and the pipelined feeder) ------

    def merge_pending_overflow(self, batch: EventBatch) -> EventBatch:
        """Fold the parked overflow backlog AHEAD of `batch` (per-device
        order: requeued rows predate the new batch's rows) and clear it.
        The merge is an arena concat — the returned batch is a set of
        views into reused buffers, valid until the next merge; route it
        immediately."""
        if self._overflow is None:
            return batch
        merged = self._merge_arena.concat([self._overflow, batch])
        self._overflow = None
        return merged

    def park_overflow(self, batch: EventBatch, over_rows: np.ndarray) -> None:
        """Park `batch`'s capacity-overflow rows (flat indices from
        route_batch) for the next merge. Fancy-index copies — safe even
        when `batch` is an arena view about to be overwritten."""
        self._overflow = self._slice_flat(batch, over_rows)

    def _one_step(self, params, prepared: "_PreparedStep"
                  ) -> Tuple["RoutedBlobView", ProcessOutputs]:
        return self.dispatch_staged(params, self.stage_prepared(prepared))

    def stage_prepared(self, prepared: "_PreparedStep",
                       order: Optional[int] = None,
                       use_ring: bool = True) -> "_StagedStep":
        """Start the host->mesh transfer of a prepared step WITHOUT
        dispatching it. device_put is async on accelerator runtimes, so a
        pipelined feeder can overlap this staging (and the host prep that
        produced the blob) with the previous step's device execution —
        the sharded half of pipeline/feed.py's double-buffered contract.
        Returns a staged handle for dispatch_staged; a pooled blob's
        release is wired there (its H2D guard is the step's output).

        The transfer goes through the H2D staging ring: `order` is the
        feeder's sequence so slots are granted in dispatch order, and
        overflow-drain blobs bypass the ring (`use_ring=False`) — a
        drain blob dispatches before its step reaches the ready heap, so
        blocking on a slot held by its own siblings would self-deadlock;
        the first blob of each step still provides the backpressure."""
        rec = prepared.flight
        if prepared.kind == "device":
            # UNROUTED flat blob, split along the LANE axis: shard i's
            # chunk is flat lanes [i*B, (i+1)*B) — the routing prologue
            # inside the step exchanges rows to their owners
            flat = NamedSharding(self.mesh, P(None, SHARD_AXIS))
            slot = self._acquire_staging_slot(rec, order, use_ring)
            if rec is not None:
                rec.begin_stage("h2d")
            try:
                blob = self._h2d_with_retry(
                    lambda: jax.device_put(prepared.blob, flat))
            except BaseException:
                if slot is not None:
                    self.staging_ring.release(slot)
                raise
            finally:
                if rec is not None:
                    rec.end_stage("h2d")
            if slot is not None:
                slot.device_blob = blob
            view = DeviceRoutedView(prepared.blob, self.router)
            return _StagedStep(blob, view, prepared.blob, prepared.blob,
                               kind="device", flight=rec, slot=slot)
        return self.stage_routed_blob(prepared.blob, flight_rec=rec,
                                      order=order, use_ring=use_ring)

    def stage_routed_blob(self, routed_blob: np.ndarray,
                          flight_rec=None, order: Optional[int] = None,
                          use_ring: bool = True) -> "_StagedStep":
        """Start the host->mesh transfer of a HOST-routed [S, WIRE_ROWS,
        B] blob (see stage_prepared; this is the host-arena half, and the
        only one multi-process feeding uses)."""
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        slot = self._acquire_staging_slot(flight_rec, order, use_ring)
        if flight_rec is not None:
            flight_rec.begin_stage("h2d")
        try:
            if self.is_multiprocess:
                # Per-host feeding (the multi-host jax data contract): this
                # process stages ONLY its local shards' rows; rows routed to
                # shards on other processes are stashed for take_foreign()
                # (the caller forwards them over the bus edge —
                # at-least-once, never dropped here).
                local = self.local_shards
                self._stash_foreign(routed_blob)
                local_blob = np.ascontiguousarray(routed_blob[local])
                # the view holds the local copy; the pooled routed blob is
                # fully consumed at this point and can go back on the shelf
                self.router.release_staging_buffer(routed_blob)
                blob = self._h2d_with_retry(
                    lambda: jax.make_array_from_process_local_data(
                        shard0, local_blob, routed_blob.shape))
                view = RoutedBlobView(local_blob, shard_ids=local)
                counted = local_blob
            else:
                blob = self._h2d_with_retry(
                    lambda: jax.device_put(routed_blob, shard0))
                # release wired after the step runs, carrying the step
                # output as the transfer-completion guard
                view = RoutedBlobView(routed_blob)
                counted = routed_blob
        except BaseException:
            if slot is not None:
                self.staging_ring.release(slot)
            raise
        finally:
            if flight_rec is not None:
                flight_rec.end_stage("h2d")
        if slot is not None:
            slot.device_blob = blob
        return _StagedStep(blob, view, counted, routed_blob,
                           flight=flight_rec, slot=slot)

    def dispatch_staged(self, params, staged: "_StagedStep"
                        ) -> Tuple["RoutedBlobView", ProcessOutputs]:
        """Dispatch the fused collective step on a staged blob (state
        donation preserved — the jitted program is unchanged)."""
        from sitewhere_tpu.ops.pack import _VALID_SHIFT

        view = staged.view
        step = (self._sharded_step_device if staged.kind == "device"
                else self._sharded_step)
        rec = staged.flight
        if rec is None:
            rec = self.flight.begin_step(engine=self.name)
        rec.begin_stage("dispatch")
        # h2d_error is staged separately here (stage_prepared /
        # stage_routed_blob) — only the dispatch point arms on this edge
        try:
            outputs = self._dispatch_with_retry(
                lambda: step(params, self._state, self._rule_state,
                             self._model_state, self._actuation_state,
                             staged.blob),
                points=("dispatch_error",))
        except BaseException:
            if staged.slot is not None:
                # guard-free: a failed step never recycles the slot's
                # array into anything — next reuse just drops it
                self.staging_ring.release(staged.slot)
            raise
        rec.end_stage("dispatch")
        if staged.slot is not None:
            # the step executed => its input transfer completed; the
            # output's readiness is the slot's reuse guard
            self.staging_ring.release(staged.slot, outputs.processed)
        self._flight_last = rec
        self._stage_hist.observe(rec.stage_s("dispatch"),
                                 engine=self.name, stage="dispatch")
        if not self.is_multiprocess and staged.routed_blob is not None:
            # pooled-blob loan (routed OR flat): returns on view GC;
            # outputs.processed is the transfer-completion guard (step
            # executed => input read)
            view._release = partial(self.router.release_staging_buffer,
                                    staged.routed_blob, outputs.processed)
        self.batches_processed += 1
        # rows actually stepped BY THIS PROCESS this call: overflow rows
        # are counted by the step that eventually carries them, so each
        # event marks exactly once. Counted from the blob head bits — the
        # full column unpack is deferred until alert materialization
        # actually needs it (most steps don't), which was ~25% of sharded
        # submit host time.
        n_events = int(
            ((staged.counted[..., 0, :] >> _VALID_SHIFT) & 1).sum())
        rec.events = n_events
        self._metrics.meter("events").mark(n_events)
        return view, outputs

    def _stash_foreign(self, routed_blob: np.ndarray) -> None:
        """Extract valid rows routed to NON-local shards as a flat batch
        with GLOBAL device indices; accumulate for take_foreign()."""
        from sitewhere_tpu.ops.pack import _VALID_SHIFT, blob_to_batch_np
        from sitewhere_tpu.parallel.router import concat_flat_batches

        others = [s for s in range(self.n_shards)
                  if s not in set(self.local_shards)]
        if not others:
            return
        sub = routed_blob[others]                       # [F, 5, B]
        if not ((sub[:, 0, :] >> _VALID_SHIFT) & 1).any():
            return
        batch = blob_to_batch_np(sub)                   # local dev indices
        shard_of = np.repeat(np.array(others, np.int32), sub.shape[-1])
        flat = jax.tree_util.tree_map(
            lambda a: np.asarray(a).reshape((-1,) + np.asarray(a).shape[2:]),
            batch)
        flat = flat.replace(
            device_idx=flat.device_idx * self.n_shards + shard_of)
        rows = np.nonzero(flat.valid)[0]
        flat = jax.tree_util.tree_map(lambda a: a[rows], flat)
        self._foreign = (flat if getattr(self, "_foreign", None) is None
                         else concat_flat_batches([self._foreign, flat]))

    def submit_routed(self, batch: EventBatch, age=None):
        """See PipelineEngine.submit_routed: sharded submit already returns
        (routed [S, B] batch, outputs)."""
        return self.submit(batch, age=age)

    def materialize_alerts(self, routed_batch: EventBatch,
                           outputs: ProcessOutputs,
                           max_alerts: Optional[int] = None
                           ) -> List[DeviceAlert]:
        """Alerts for the last submit, plus any stashed during overflow
        drain steps (see submit())."""
        pending, self._pending_alerts = self._pending_alerts, []
        return pending + self._materialize_routed(routed_batch, outputs,
                                                  max_alerts)

    def _gather_local(self, arr) -> np.ndarray:
        """Local [S_local, B, ...] block of a shard-axis-sharded output —
        each process materializes its own shards' rows only (np.asarray on
        the global array would require non-addressable shards)."""
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    def _materialize_routed(self, routed_batch,
                            outputs: ProcessOutputs,
                            max_alerts: Optional[int] = None
                            ) -> List[DeviceAlert]:
        """Materialize from the per-shard compacted alert lanes: ONE
        fixed-shape [S, ALERT_LANE_ROWS, K] fetch for the whole mesh
        (the lanes travel shard-axis-sharded with the existing outputs —
        no extra collective). Shards decode shard-major, rows ascending
        within a shard, so the alert order matches the flattened mask
        scan exactly. Accepts the lazy RoutedBlobView (sharded submit's
        return) or a plain routed EventBatch; the wire blob only unpacks
        when something actually fired. Under multi-process feeding the
        lanes gather local shard blocks only — each host materializes the
        alerts of its own devices."""
        from sitewhere_tpu.ops.compact import (
            DecodedAlertLanes, decode_alert_lanes)

        shard_ids = None
        if isinstance(routed_batch, RoutedBlobView):
            shard_ids = routed_batch.shard_ids
        rec = self._flight_last
        if rec is not None:
            rec.begin_stage("lane_fetch")
        if self.is_multiprocess:
            lanes = self._gather_local(outputs.alert_lanes)
            cmd_lanes = self._gather_local(outputs.command_lanes)
        else:
            # [S, ROWS, K] alert lanes + [S, 4, Kc] command lanes
            lanes, cmd_lanes = self._fetch_lanes_with_retry(outputs)
        if rec is not None:
            rec.end_stage("lane_fetch")
            self._stage_hist.observe(rec.stage_s("lane_fetch"),
                                     engine=self.name, stage="lane_fetch")
        self.d2h_fetches += 2
        self.d2h_bytes += lanes.nbytes + cmd_lanes.nbytes
        if rec is not None:
            rec.begin_stage("materialize")
        try:
            decs = [decode_alert_lanes(lanes[s])
                    for s in range(lanes.shape[0])]
            self._account_lane_overflow(
                sum(d.dropped_alerts for d in decs))
            self._account_route_dropped(
                sum(d.route_dropped for d in decs))
            if not any(d.n for d in decs):
                return []
            if isinstance(routed_batch, RoutedBlobView):
                routed_batch = routed_batch.batch
            dev = np.asarray(routed_batch.device_idx)        # [S_rows, B]
            ts = np.asarray(routed_batch.ts)
            S_rows, B = dev.shape
            ids = (np.arange(S_rows, dtype=np.int32) if shard_ids is None
                   else np.array(shard_ids, np.int32))
            # shard-major flat rows + the per-row GLOBAL device remap
            # (local index l on shard s is global l * S + s)
            rows_flat = np.concatenate(
                [s * B + d.rows for s, d in enumerate(decs)])
            shard_of = np.concatenate(
                [np.full(d.n, ids[s], np.int32) for s, d in enumerate(decs)])
            combined = DecodedAlertLanes(
                rows=rows_flat,
                thr_fired=np.concatenate([d.thr_fired for d in decs]),
                geo_fired=np.concatenate([d.geo_fired for d in decs]),
                thr_rule=np.concatenate([d.thr_rule for d in decs]),
                geo_rule=np.concatenate([d.geo_rule for d in decs]),
                thr_level=np.concatenate([d.thr_level for d in decs]),
                geo_level=np.concatenate([d.geo_level for d in decs]),
                fired_rows=sum(d.fired_rows for d in decs),
                dropped_alerts=sum(d.dropped_alerts for d in decs),
                total_alerts=sum(d.total_alerts for d in decs),
                prog_fired=np.concatenate([d.prog_fired for d in decs]),
                prog_rule=np.concatenate([d.prog_rule for d in decs]),
                prog_level=np.concatenate([d.prog_level for d in decs]),
                model_fired=np.concatenate([d.model_fired for d in decs]),
                model_slot=np.concatenate([d.model_slot for d in decs]))
            dev_rows = (dev.reshape(-1)[rows_flat] * self.n_shards
                        + shard_of)
            ts_rows = ts.reshape(-1)[rows_flat]
            bounded = self._bound_alert_rows(combined, max_alerts)
            n = bounded.n
            return self._emit_alerts(bounded, dev_rows[:n], ts_rows[:n])
        finally:
            if rec is not None:
                rec.end_stage("materialize")
                self._stage_hist.observe(
                    rec.stage_s("materialize"),
                    engine=self.name, stage="materialize")
            self._materialize_commands_sharded(cmd_lanes, rec, shard_ids)
            if rec is not None:
                self._close_age(rec)

    def _materialize_commands_sharded(self, cmd_lanes: np.ndarray, rec,
                                      shard_ids) -> None:
        """Decode the per-shard command lanes ([S, 4, Kc], same fetch as
        the alert lanes) and resolve fires with GLOBAL device indices
        (local l on shard s is global l * S + s); accounting, token
        resolution, and fan-out are shared with the single-chip engine.
        Rows remap shard-major like the alert lanes so the fire order
        matches the flattened oracle scan."""
        from sitewhere_tpu.ops.actuate import (
            DecodedCommandLanes, decode_command_lanes)

        if rec is not None:
            rec.begin_stage("actuate")
        try:
            S = cmd_lanes.shape[0]
            ids = (np.arange(S, dtype=np.int32) if shard_ids is None
                   else np.array(shard_ids, np.int32))
            decs = [decode_command_lanes(cmd_lanes[s]) for s in range(S)]
            B = self.batch_size
            combined = DecodedCommandLanes(
                rows=np.concatenate(
                    [s * B + d.rows for s, d in enumerate(decs)]),
                policy_slot=np.concatenate(
                    [d.policy_slot for d in decs]),
                level=np.concatenate([d.level for d in decs]),
                source=np.concatenate([d.source for d in decs]),
                dev=np.concatenate(
                    [d.dev * self.n_shards + ids[s]
                     for s, d in enumerate(decs)]),
                fired=sum(d.fired for d in decs),
                dropped=sum(d.dropped for d in decs),
                debounced=sum(d.debounced for d in decs))
            self._account_command_activity(combined)
            fires = (self._emit_command_fires(combined)
                     if combined.n else [])
            if rec is not None:
                rec.commands = len(fires)
        finally:
            if rec is not None:
                rec.end_stage("actuate")
                self._stage_hist.observe(rec.stage_s("actuate"),
                                         engine=self.name, stage="actuate")
        self._fanout_commands(fires, rec)

    def _account_route_dropped(self, dropped: int) -> None:
        """Defensive on-device route drop accounting (lane counts slot 3,
        ops/route.py): the host lane-fit guard makes this zero on every
        normal step, so any nonzero count is loud — it means a row was
        lost between the guard and the exchange (a bug, not weather)."""
        if not dropped:
            return
        self.device_route_dropped += dropped
        self._metrics.counter("route.device_dropped").inc(dropped)
        import logging
        logging.getLogger("sitewhere.parallel").error(
            "device route dropped %d rows past the %d-slot lanes despite "
            "the host fit guard (device_route_dropped=%d total) — "
            "investigate: the guard and the kernel disagree",
            dropped, self.route_lane_capacity, self.device_route_dropped)

    # -- reads ----------------------------------------------------------------

    _STATE_ROW_FIELDS = ("last_interaction", "present",
                         "presence_missing_since", "event_count",
                         "last_location", "last_location_ts",
                         "last_measurement", "last_measurement_ts",
                         "last_alert_type", "last_alert_level",
                         "last_alert_ts")

    def _state_row(self, idx: int):
        s, l = idx % self.n_shards, idx // self.n_shards

        class Row:
            pass

        row = Row()
        with self._state_lock:  # vs concurrent donation (base __init__)
            state = self._state
            if self.is_multiprocess:
                # Multi-controller jax is SPMD: per-process single-element
                # indexing of a distributed array is NOT a valid program
                # (each process would issue a different computation).
                # Read straight from the addressable shard's host data; a
                # device owned by another host returns None (query that
                # host — device ownership is static, d % S).
                if s not in self.local_shards:
                    return None
                for field_name in self._STATE_ROW_FIELDS:
                    arr = getattr(state, field_name)
                    block = next(
                        sh for sh in arr.addressable_shards
                        if (sh.index[0].start or 0) == s)
                    setattr(row, field_name,
                            np.asarray(block.data)[0, l])
                return row
            for field_name in self._STATE_ROW_FIELDS:
                setattr(row, field_name,
                        np.asarray(getattr(state, field_name)[s, l]))
        return row

    def presence_sweep(self) -> List[str]:
        params = self._ensure_params()
        now_rel = np.int32(self.packer.rel_ts(int(time.time() * 1000)))
        registered = params.assignment_status == 1
        with self._state_lock:
            self._state, newly_missing = self._presence(
                self._state, registered, now_rel,
                np.int32(min(self.presence_missing_interval_ms, 2 ** 31 - 1)))
        if self.is_multiprocess:
            # each host sweeps (and notifies for) its LOCAL shards only
            missing_np = self._gather_local(newly_missing)
            shard_ids = np.array(self.local_shards, np.int32)
        else:
            missing_np = np.asarray(newly_missing)
            shard_ids = np.arange(self.n_shards, dtype=np.int32)
        rows, locals_ = np.nonzero(missing_np)
        if rows.size == 0:
            return []
        # vectorized: global index = local * S + shard, one fancy index
        # into the cached token array (no per-row token_of loop)
        global_idx = locals_ * self.n_shards + shard_ids[rows]
        tokens = self.registry.devices.token_array()[global_idx].tolist()
        return [t for t in tokens if t]

    # -- elastic checkpoint layout ----------------------------------------

    _TENANT_STATE_FIELDS = ("tenant_event_count", "tenant_alert_count")

    def canonical_state(self) -> DeviceStateTensors:
        """Flat device-major snapshot: device-indexed tensors un-shard via
        the router layout (global d lives at (d % S, d // S)); per-shard
        tenant counters are additive and sum to the global totals. The
        result is bit-identical to a single-chip engine that processed the
        same events — a checkpoint taken on ANY mesh restores onto ANY
        other (elastic recovery)."""
        import dataclasses as _dc

        import jax.numpy as jnp

        if self.is_multiprocess:
            # graceful degradation, not a 500 traceback: a live multi-host
            # canonical gather would need a collective inside the lockstep
            # protocol. SiteWhereError carries a structured code +
            # http_status, so the REST layer surfaces the offline recipe
            # as a 409 with the command the operator actually needs.
            from sitewhere_tpu.errors import ErrorCode, SiteWhereError

            raise SiteWhereError(
                "multi-host canonical gather is not available on a live "
                "cluster (it would need a collective inside the lockstep "
                "protocol); each host saves its own shard blocks "
                "(local_state_shards — no collective, any host any time). "
                "Merge every host's checkpoint into the canonical "
                "any-topology snapshot offline with the assemble-checkpoint "
                "recipe: `python -m sitewhere_tpu assemble-checkpoint "
                "<host0-ckpt> <host1-ckpt> ... --out <dir>`",
                ErrorCode.GENERIC, http_status=409)
        # device-side copy under the lock only (see base canonical_state);
        # the D2H gather + host re-layout run outside it
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self._state)
        out = {}
        for f in _dc.fields(snap):
            a = np.asarray(getattr(snap, f.name))
            out[f.name] = (a.sum(0, dtype=a.dtype)
                           if f.name in self._TENANT_STATE_FIELDS
                           else self.router.unshard_param(a))
        return DeviceStateTensors(**out)

    def _canonical_shape_of(self, field_name: str):
        # resident layout is stacked [S, L, ...]; canonical flattens the
        # device axes ([S*L, ...]); tenant counters lose the shard axis
        c = getattr(self._state, field_name).shape
        if field_name in self._TENANT_STATE_FIELDS:
            return c[1:]
        return (c[0] * c[1],) + tuple(c[2:])

    def load_canonical_state(self, state: DeviceStateTensors) -> None:
        """Re-shard a flat snapshot onto this engine's mesh. Tenant
        counters (additive) land on shard 0; device tensors re-lay to the
        (d % S, d // S) owner. Dimensions validated by
        _validate_canonical (shared with the single-chip engine)."""
        import dataclasses as _dc

        self._validate_canonical(state)
        S = self.n_shards
        out = {}
        for f in _dc.fields(state):
            a = np.asarray(getattr(state, f.name))
            if f.name in self._TENANT_STATE_FIELDS:
                stacked = np.zeros((S,) + a.shape, a.dtype)
                stacked[0] = a
                out[f.name] = stacked
            else:
                out[f.name] = self.router.shard_param(a)
        stacked_state = DeviceStateTensors(**out)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        with self._state_lock:
            self._state = _put_global_tree(
                stacked_state, _tree_specs(stacked_state, shard0))

    def set_state(self, state: DeviceStateTensors) -> None:
        """The sharded engine's resident layout is stacked [S, D/S, ...];
        checkpoints use the flat canonical layout — there is no native
        set_state. Use load_canonical_state (flat) explicitly."""
        raise TypeError(
            "ShardedPipelineEngine state is mesh-resident; restore flat "
            "canonical snapshots via load_canonical_state()")

    # -- per-host shard checkpoint layout (multi-host gang restart) --------

    def local_state_shards(self):
        """(local shard ids, {field: [S_local, ...] blocks}) — THIS host's
        slice of the device state, read via addressable shards only (pure
        local D2H; no collective, so any host checkpoints at any time
        without lockstep). The per-host complement of canonical_state:
        each host of a gang-restarting cluster saves its own blocks and
        restores them onto the SAME topology (elastic any-mesh restores
        stay the single-controller canonical layout's job)."""
        import dataclasses as _dc

        with self._state_lock:
            state = self._state
            blocks = {}
            for f in _dc.fields(state):
                arr = getattr(state, f.name)
                if self.is_multiprocess:
                    blocks[f.name] = self._gather_local(arr)
                else:
                    blocks[f.name] = np.asarray(arr)
        return list(self.local_shards), blocks

    def load_local_state_shards(self, shard_ids, blocks) -> None:
        """Inverse of local_state_shards on the same mesh topology: place
        this host's blocks back onto its local devices
        (make_array_from_process_local_data — local transfers only)."""
        import dataclasses as _dc

        if list(shard_ids) != list(self.local_shards):
            raise ValueError(
                f"host-shard checkpoint was taken for shards {shard_ids}; "
                f"this process owns {self.local_shards} — per-host "
                f"checkpoints restore onto the same cluster topology only "
                f"(use a single-controller canonical checkpoint to change "
                f"topology)")
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        out = {}
        for f in _dc.fields(DeviceStateTensors):
            local = np.ascontiguousarray(blocks[f.name])
            expect = getattr(self._state, f.name).shape
            global_shape = (self.n_shards,) + tuple(local.shape[1:])
            if tuple(global_shape) != tuple(expect):
                raise ValueError(
                    f"host-shard checkpoint field {f.name}: global shape "
                    f"{global_shape} != engine {tuple(expect)}")
            if self.is_multiprocess:
                out[f.name] = jax.make_array_from_process_local_data(
                    shard0, local, global_shape)
            else:
                out[f.name] = jax.device_put(local, shard0)
        with self._state_lock:
            self._state = DeviceStateTensors(**out)

    # -- rule-program state layouts ----------------------------------------

    _RULE_STATE_DEVICE_FIELDS = ("slab",)
    _RULE_STATE_PROGRAM_FIELDS = ("gen", "fire_count", "suppress_count")

    def canonical_rule_state(self):
        """Flat device-major rule-program state snapshot, mirroring
        canonical_state: device-indexed lanes un-shard via the router
        layout; per-shard fire/suppress counters (additive partials) sum;
        `gen` takes the per-slot max (every shard steps in lockstep, so
        they agree whenever a step has run since the last install)."""
        import dataclasses as _dc

        import jax.numpy as jnp

        if self._rule_state is None:
            return None
        if self.is_multiprocess:
            from sitewhere_tpu.errors import ErrorCode, SiteWhereError

            raise SiteWhereError(
                "multi-host canonical gather is not available on a live "
                "cluster; merge per-host checkpoints offline with "
                "assemble-checkpoint", ErrorCode.GENERIC, http_status=409)
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self._rule_state)
        out = {}
        for f in _dc.fields(snap):
            a = np.asarray(getattr(snap, f.name))
            if f.name in ("fire_count", "suppress_count"):
                out[f.name] = a.sum(0, dtype=a.dtype)
            elif f.name == "gen":
                out[f.name] = a.max(0)
            else:
                out[f.name] = self.router.unshard_param(a)
        from sitewhere_tpu.ops.stateful import RuleStateTensors
        return RuleStateTensors(**out)

    def load_canonical_rule_state(self, rule_state) -> None:
        import dataclasses as _dc

        from sitewhere_tpu.ops.stateful import RuleStateTensors

        self._validate_canonical_rule_state(rule_state)
        S = self.n_shards
        out = {}
        for f in _dc.fields(RuleStateTensors):
            a = np.asarray(getattr(rule_state, f.name))
            if f.name in self._RULE_STATE_PROGRAM_FIELDS:
                stacked = np.zeros((S,) + a.shape, a.dtype)
                if f.name == "gen":
                    # generations must match on EVERY shard or the next
                    # step's stale check would wipe the restored state
                    stacked[:] = a
                else:
                    stacked[0] = a  # additive counters land on shard 0
                out[f.name] = stacked
            else:
                out[f.name] = self.router.shard_param(a)
        stacked_state = RuleStateTensors(**out)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        with self._state_lock:
            self._rule_state = _put_global_tree(
                stacked_state, _tree_specs(stacked_state, shard0))
            self._rule_state_built_dims = self._rule_state_dims()

    def local_rule_state_blocks(self):
        """THIS host's shard blocks of the rule-program state (the
        per-host complement of canonical_rule_state; same contract as
        local_state_shards — pure local D2H, no collective)."""
        import dataclasses as _dc

        if self._rule_state is None:
            return None
        with self._state_lock:
            blocks = {}
            for f in _dc.fields(self._rule_state):
                arr = getattr(self._rule_state, f.name)
                blocks[f.name] = (self._gather_local(arr)
                                  if self.is_multiprocess
                                  else np.asarray(arr))
        return blocks

    def load_local_rule_state_blocks(self, blocks) -> None:
        import dataclasses as _dc

        from sitewhere_tpu.ops.stateful import RuleStateTensors

        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        S = self.n_shards
        canonical = self._expected_rule_state_shapes()
        out = {}
        for f in _dc.fields(RuleStateTensors):
            local = np.ascontiguousarray(blocks[f.name])
            flat = canonical[f.name]
            expect = ((S, flat[0] // S) + flat[1:]
                      if f.name not in self._RULE_STATE_PROGRAM_FIELDS
                      else (S,) + flat)
            global_shape = (S,) + tuple(local.shape[1:])
            if tuple(global_shape) != tuple(expect):
                raise ValueError(
                    f"host-shard rule-state field {f.name}: global shape "
                    f"{global_shape} != engine {tuple(expect)}")
            if self.is_multiprocess:
                out[f.name] = jax.make_array_from_process_local_data(
                    shard0, local, global_shape)
            else:
                out[f.name] = jax.device_put(local, shard0)
        with self._state_lock:
            self._rule_state = RuleStateTensors(**out)
            self._rule_state_built_dims = self._rule_state_dims()

    # -- anomaly-model state layouts ---------------------------------------

    _MODEL_STATE_DEVICE_FIELDS = ("slab",)
    _MODEL_STATE_MODEL_FIELDS = ("gen", "fire_count", "eval_count")

    def canonical_model_state(self):
        """Flat device-major anomaly-model state snapshot, mirroring
        canonical_rule_state: device-indexed feature lanes un-shard via
        the router layout; per-shard fire/eval counters (additive
        partials) sum; `gen` takes the per-slot max (shards step in
        lockstep, so they agree whenever a step has run since the last
        install)."""
        import dataclasses as _dc

        import jax.numpy as jnp

        if self._model_state is None:
            return None
        if self.is_multiprocess:
            from sitewhere_tpu.errors import ErrorCode, SiteWhereError

            raise SiteWhereError(
                "multi-host canonical gather is not available on a live "
                "cluster; merge per-host checkpoints offline with "
                "assemble-checkpoint", ErrorCode.GENERIC, http_status=409)
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self._model_state)
        out = {}
        for f in _dc.fields(snap):
            a = np.asarray(getattr(snap, f.name))
            if f.name in ("fire_count", "eval_count"):
                out[f.name] = a.sum(0, dtype=a.dtype)
            elif f.name == "gen":
                out[f.name] = a.max(0)
            else:
                out[f.name] = self.router.unshard_param(a)
        from sitewhere_tpu.ops.anomaly import ModelStateTensors
        return ModelStateTensors(**out)

    def load_canonical_model_state(self, model_state) -> None:
        import dataclasses as _dc

        from sitewhere_tpu.ops.anomaly import ModelStateTensors

        self._validate_canonical_model_state(model_state)
        S = self.n_shards
        out = {}
        for f in _dc.fields(ModelStateTensors):
            a = np.asarray(getattr(model_state, f.name))
            if f.name in self._MODEL_STATE_MODEL_FIELDS:
                stacked = np.zeros((S,) + a.shape, a.dtype)
                if f.name == "gen":
                    # generations must match on EVERY shard or the next
                    # step's stale check would wipe the restored rows
                    stacked[:] = a
                else:
                    stacked[0] = a  # additive counters land on shard 0
                out[f.name] = stacked
            else:
                out[f.name] = self.router.shard_param(a)
        stacked_state = ModelStateTensors(**out)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        with self._state_lock:
            self._model_state = _put_global_tree(
                stacked_state, _tree_specs(stacked_state, shard0))
            self._model_state_built_dims = self._model_state_dims()

    def local_model_state_blocks(self):
        """THIS host's shard blocks of the anomaly-model state (the
        per-host complement of canonical_model_state; same contract as
        local_state_shards — pure local D2H, no collective)."""
        import dataclasses as _dc

        if self._model_state is None:
            return None
        with self._state_lock:
            blocks = {}
            for f in _dc.fields(self._model_state):
                arr = getattr(self._model_state, f.name)
                blocks[f.name] = (self._gather_local(arr)
                                  if self.is_multiprocess
                                  else np.asarray(arr))
        return blocks

    def load_local_model_state_blocks(self, blocks) -> None:
        import dataclasses as _dc

        from sitewhere_tpu.ops.anomaly import ModelStateTensors

        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        S = self.n_shards
        canonical = self._expected_model_state_shapes()
        out = {}
        for f in _dc.fields(ModelStateTensors):
            local = np.ascontiguousarray(blocks[f.name])
            flat = canonical[f.name]
            expect = ((S, flat[0] // S) + flat[1:]
                      if f.name not in self._MODEL_STATE_MODEL_FIELDS
                      else (S,) + flat)
            global_shape = (S,) + tuple(local.shape[1:])
            if tuple(global_shape) != tuple(expect):
                raise ValueError(
                    f"host-shard model-state field {f.name}: global shape "
                    f"{global_shape} != engine {tuple(expect)}")
            if self.is_multiprocess:
                out[f.name] = jax.make_array_from_process_local_data(
                    shard0, local, global_shape)
            else:
                out[f.name] = jax.device_put(local, shard0)
        with self._state_lock:
            self._model_state = ModelStateTensors(**out)
            self._model_state_built_dims = self._model_state_dims()

    _ACTUATION_STATE_POLICY_FIELDS = ("gen", "fire_count", "debounce_count")

    def canonical_actuation_state(self):
        """Flat device-major actuation debounce-state snapshot, mirroring
        canonical_model_state: device-indexed slab lanes un-shard via the
        router layout; per-shard fire/debounce counters (additive
        partials) sum; `gen` takes the per-slot max."""
        import dataclasses as _dc

        import jax.numpy as jnp

        if self._actuation_state is None:
            return None
        if self.is_multiprocess:
            from sitewhere_tpu.errors import ErrorCode, SiteWhereError

            raise SiteWhereError(
                "multi-host canonical gather is not available on a live "
                "cluster; merge per-host checkpoints offline with "
                "assemble-checkpoint", ErrorCode.GENERIC, http_status=409)
        with self._state_lock:
            snap = jax.tree_util.tree_map(jnp.copy, self._actuation_state)
        out = {}
        for f in _dc.fields(snap):
            a = np.asarray(getattr(snap, f.name))
            if f.name in ("fire_count", "debounce_count"):
                out[f.name] = a.sum(0, dtype=a.dtype)
            elif f.name == "gen":
                out[f.name] = a.max(0)
            else:
                out[f.name] = self.router.unshard_param(a)
        from sitewhere_tpu.ops.actuate import ActuationStateTensors
        return ActuationStateTensors(**out)

    def load_canonical_actuation_state(self, actuation_state) -> None:
        import dataclasses as _dc

        from sitewhere_tpu.ops.actuate import ActuationStateTensors

        self._validate_canonical_actuation_state(actuation_state)
        S = self.n_shards
        out = {}
        for f in _dc.fields(ActuationStateTensors):
            a = np.asarray(getattr(actuation_state, f.name))
            if f.name in self._ACTUATION_STATE_POLICY_FIELDS:
                stacked = np.zeros((S,) + a.shape, a.dtype)
                if f.name == "gen":
                    # generations must match on EVERY shard or the next
                    # step's stale check would wipe the restored rows
                    stacked[:] = a
                else:
                    stacked[0] = a  # additive counters land on shard 0
                out[f.name] = stacked
            else:
                out[f.name] = self.router.shard_param(a)
        stacked_state = ActuationStateTensors(**out)
        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        with self._state_lock:
            self._actuation_state = _put_global_tree(
                stacked_state, _tree_specs(stacked_state, shard0))
            self._actuation_state_built_dims = self._actuation_state_dims()

    def local_actuation_state_blocks(self):
        """THIS host's shard blocks of the actuation debounce state (the
        per-host complement of canonical_actuation_state; pure local D2H,
        no collective)."""
        import dataclasses as _dc

        if self._actuation_state is None:
            return None
        with self._state_lock:
            blocks = {}
            for f in _dc.fields(self._actuation_state):
                arr = getattr(self._actuation_state, f.name)
                blocks[f.name] = (self._gather_local(arr)
                                  if self.is_multiprocess
                                  else np.asarray(arr))
        return blocks

    def load_local_actuation_state_blocks(self, blocks) -> None:
        import dataclasses as _dc

        from sitewhere_tpu.ops.actuate import ActuationStateTensors

        shard0 = NamedSharding(self.mesh, P(SHARD_AXIS))
        S = self.n_shards
        canonical = self._expected_actuation_state_shapes()
        out = {}
        for f in _dc.fields(ActuationStateTensors):
            local = np.ascontiguousarray(blocks[f.name])
            flat = canonical[f.name]
            expect = ((S, flat[0] / S) + flat[1:]
                      if f.name not in self._ACTUATION_STATE_POLICY_FIELDS
                      else (S,) + flat)
            global_shape = (S,) + tuple(local.shape[1:])
            if tuple(global_shape) != tuple(expect):
                raise ValueError(
                    f"host-shard actuation-state field {f.name}: global "
                    f"shape {global_shape} != engine {tuple(expect)}")
            if self.is_multiprocess:
                out[f.name] = jax.make_array_from_process_local_data(
                    shard0, local, global_shape)
            else:
                out[f.name] = jax.device_put(local, shard0)
        with self._state_lock:
            self._actuation_state = ActuationStateTensors(**out)
            self._actuation_state_built_dims = self._actuation_state_dims()

    def pending_overflow_batch(self) -> Optional[EventBatch]:
        """The parked overflow rows as a flat host batch (checkpoint saves
        them verbatim when draining is impossible — multi-host lockstep)."""
        return self._overflow

    def set_pending_overflow_batch(self, batch: Optional[EventBatch]) -> None:
        self._overflow = batch

    def drain_pending(self) -> int:
        """Fold any parked overflow backlog into device state (empty-batch
        drain steps). Checkpoint save calls this first: backlogged rows'
        bus offsets may already be committed, so a snapshot that omitted
        them would break the offsets<=state invariant. Alerts fired by the
        drained events stash on _pending_alerts (picked up by the next
        materialize_alerts; PipelineCheckpointer.save also persists the
        stash in the manifest, so a crash before pickup recovers them)
        with the same bounded-room accounting as submit()'s internal
        drain — never silently lost. Returns the number of drain steps
        run."""
        from sitewhere_tpu.ops.pack import empty_batch

        if self.is_multiprocess:
            # a host-local drain loop would run a varying number of
            # collective steps per host (lockstep violation); the cluster
            # checkpoint instead snapshots the pending overflow batch
            # itself (parallel/cluster.py checkpoint path)
            raise RuntimeError(
                "drain_pending is single-controller only; multi-host "
                "checkpoints persist the overflow batch in the manifest")
        steps = 0
        while self.pending_overflow > 0:
            routed, outputs = self.submit(empty_batch(1))
            self._stash_pending_alerts(
                self._materialize_routed(routed, outputs))
            steps += 1
        return steps

    def _stash_pending_alerts(self, alerts: List[DeviceAlert]) -> None:
        """Bounded-room stash shared by submit()'s internal drain and
        drain_pending: overflow past max_pending_alerts is counted on
        alerts_dropped, never silently truncated."""
        room = self.max_pending_alerts - len(self._pending_alerts)
        if len(alerts) > room:
            dropped = len(alerts) - max(0, room)
            self.alerts_dropped += dropped
            self._metrics.counter("alerts.dropped").inc(dropped)
        self._pending_alerts.extend(alerts[:max(0, room)])

    @property
    def pending_overflow(self) -> int:
        return 0 if self._overflow is None else int(self._overflow.valid.sum())

    def stats(self):
        with self._state_lock:  # tenant-count reads vs donation
            s = self._state
            if self.is_multiprocess:
                # per-process view: counts of THIS host's shards (global
                # totals need an allgather; tenant psums per step already
                # travel replicated in ProcessOutputs.tenant_counts)
                tenant_events = self._gather_local(
                    s.tenant_event_count).sum(0).tolist()
                tenant_alerts = self._gather_local(
                    s.tenant_alert_count).sum(0).tolist()
            else:
                tenant_events = np.asarray(
                    s.tenant_event_count).sum(0).tolist()
                tenant_alerts = np.asarray(
                    s.tenant_alert_count).sum(0).tolist()
        return {
            "batches": self.batches_processed,
            "dropped": self.total_dropped,
            "drain_steps": self.drain_steps,
            "pending_overflow": self.pending_overflow,
            # on-device shard routing accounting (ops/route.py):
            # fallbacks = steps the skew guard spilled to the host arena
            # path; route_dropped stays 0 unless guard and kernel disagree
            "device_routing": self.device_routing,
            "device_route_steps": self.device_route_steps,
            "device_route_fallbacks": self.device_route_fallbacks,
            "device_route_dropped": self.device_route_dropped,
            "tenant_event_count": tenant_events,
            "tenant_alert_count": tenant_alerts,
            # multi-process: tenant totals above cover THIS host's shards
            # only (global totals need an allgather); REST/admin readers
            # must not misread per-host partials as global
            "scope": "local" if self.is_multiprocess else "global",
        }
