"""Mesh construction: one `shard` axis over all available devices.

The hot path is embarrassingly parallel over devices (each shard owns a
disjoint slice of the device population), so a 1-D mesh suffices; tenants ride
the same axis (a tenant's devices spread over all shards, stats psum'd).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def make_mesh(n_shards: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_shards is not None:
        if n_shards > len(devs):
            raise ValueError(f"requested {n_shards} shards, have {len(devs)} devices")
        devs = devs[:n_shards]
    return Mesh(np.asarray(devs), (SHARD_AXIS,))


def shard_axis_size(mesh: Mesh) -> int:
    return mesh.shape[SHARD_AXIS]
