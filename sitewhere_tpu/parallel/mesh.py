"""Mesh construction: one `shard` axis over all available devices.

The hot path is embarrassingly parallel over devices (each shard owns a
disjoint slice of the device population), so a 1-D mesh suffices; tenants ride
the same axis (a tenant's devices spread over all shards, stats psum'd).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def _cpu_requested() -> bool:
    """True when this process asked jax for the cpu platform (env var or
    config) — the only situation in which substituting virtual CPU devices
    for a too-small default-device list is what the caller meant."""
    import os

    want = (os.environ.get("JAX_PLATFORMS", "")
            + (jax.config.jax_platforms or ""))
    return "cpu" in want


def make_mesh(n_shards: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_shards is not None:
        if n_shards > len(devs) and devices is None and _cpu_requested():
            # Some TPU plugins ignore JAX_PLATFORMS=cpu (jax.devices() still
            # returns the accelerator); the forced host-platform devices are
            # still present on the cpu backend. The fallback engages ONLY
            # when the caller asked for cpu (env or config) and the plugin
            # ignored it — a production accelerator host with too few chips
            # still fails fast below rather than silently running on CPU.
            cpu = jax.devices("cpu")
            if len(cpu) >= n_shards:
                logging.getLogger("sitewhere.parallel").warning(
                    "make_mesh: only %d default-backend device(s) for %d "
                    "shards; falling back to %d virtual CPU devices",
                    len(devs), n_shards, len(cpu))
                devs = cpu
        if n_shards > len(devs):
            raise ValueError(
                f"requested {n_shards} shards, have {len(devs)} devices "
                f"(cpu backend has {len(jax.devices('cpu'))})")
        devs = devs[:n_shards]
    return Mesh(np.asarray(devs), (SHARD_AXIS,))


def shard_axis_size(mesh: Mesh) -> int:
    return mesh.shape[SHARD_AXIS]
