"""Device<->cloud wire protocol: framing + payload codecs.

Reference: sitewhere-communication/src/main/proto/sitewhere.proto —
device->cloud `SiteWhere.Command` (SEND_REGISTRATION, SEND_ACKNOWLEDGEMENT,
SEND_DEVICE_MEASUREMENTS, SEND_DEVICE_LOCATION, SEND_DEVICE_ALERT,
SEND_DEVICE_STREAM, SEND_DEVICE_STREAM_DATA, REQUEST_DEVICE_STREAM_DATA) and
cloud->device `Device.Command` (ACK_REGISTRATION, RECEIVE_DEVICE_COMMAND...),
with event payloads Model.DeviceMeasurements/DeviceLocation/DeviceAlert.

Frame layout (little-endian):

    0..1   magic  b"SW"
    2      version (1)
    3      msg_type (MessageType)
    4..7   u32 payload length
    8..    payload

Hot event payloads (MEASUREMENT / LOCATION / ALERT) are fixed-layout binary —
decodable straight into SoA columns by `decode_event_frames_to_columns`
(and by the C++ batch decoder in native/, which implements the same layout):

    u8 token_len, token, i64 event_ts_ms, then per type:
      MEASUREMENT: u8 name_len, name, f32 value
      LOCATION:    f32 lat, f32 lon, f32 elevation
      ALERT:       u8 type_len, type, u8 level, u16 msg_len, msg

Control payloads (REGISTER, REGISTER_ACK, COMMAND, COMMAND_RESPONSE, ACK,
STREAM_DATA) are msgpack maps — the flexibility protobuf gives the
reference, without a schema compiler in the device SDK.
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import msgpack
import numpy as np

MAGIC = b"SW"
VERSION = 1
_HEADER = struct.Struct("<2sBBI")


class WireError(Exception):
    pass


class MessageType(enum.IntEnum):
    # device -> cloud (SiteWhere.Command in sitewhere.proto:10-21)
    REGISTER = 1
    ACK = 2
    MEASUREMENT = 3
    LOCATION = 4
    ALERT = 5
    STREAM_DATA = 6
    COMMAND_RESPONSE = 7
    # cloud -> device (Device.Command in sitewhere.proto:100-110)
    REGISTER_ACK = 16
    COMMAND = 17
    STREAM_ACK = 18


HOT_TYPES = (MessageType.MEASUREMENT, MessageType.LOCATION, MessageType.ALERT)


def encode_frame(msg_type: MessageType, payload: bytes) -> bytes:
    return _HEADER.pack(MAGIC, VERSION, int(msg_type), len(payload)) + payload


# Upper bound on a single frame's payload: caps what a stream transport
# will buffer for one frame, so a corrupt/hostile length header can't grow
# RSS unboundedly (largest legitimate frame is a stream-data chunk).
MAX_FRAME_PAYLOAD = 16 * 1024 * 1024


def decode_frames(data: bytes) -> Tuple[List[Tuple[MessageType, bytes]], bytes]:
    """Parse as many complete frames as present; returns (frames, remainder)
    so stream transports can carry partial tails across reads."""
    frames: List[Tuple[MessageType, bytes]] = []
    pos = 0
    n = len(data)
    while pos + _HEADER.size <= n:
        magic, version, mtype, length = _HEADER.unpack_from(data, pos)
        if magic != MAGIC or version != VERSION:
            raise WireError(f"bad frame header at {pos}")
        if length > MAX_FRAME_PAYLOAD:
            raise WireError(f"frame payload {length} exceeds cap")
        if pos + _HEADER.size + length > n:
            break
        payload = data[pos + _HEADER.size:pos + _HEADER.size + length]
        frames.append((MessageType(mtype), payload))
        pos += _HEADER.size + length
    return frames, data[pos:]


class WireCodec:
    """Payload encode/decode for every MessageType."""

    # -- hot events: fixed binary layout -----------------------------------
    @staticmethod
    def encode_measurement(token: str, ts_ms: int, name: str,
                           value: float) -> bytes:
        tb, nb = token.encode(), name.encode()
        return (struct.pack("<B", len(tb)) + tb + struct.pack("<q", ts_ms)
                + struct.pack("<B", len(nb)) + nb + struct.pack("<f", value))

    @staticmethod
    def encode_location(token: str, ts_ms: int, lat: float, lon: float,
                        elevation: float = 0.0) -> bytes:
        tb = token.encode()
        return (struct.pack("<B", len(tb)) + tb
                + struct.pack("<qfff", ts_ms, lat, lon, elevation))

    @staticmethod
    def encode_alert(token: str, ts_ms: int, alert_type: str, level: int,
                     message: str = "") -> bytes:
        tb, ab, mb = token.encode(), alert_type.encode(), message.encode()
        return (struct.pack("<B", len(tb)) + tb + struct.pack("<q", ts_ms)
                + struct.pack("<B", len(ab)) + ab
                + struct.pack("<B", level)
                + struct.pack("<H", len(mb)) + mb)

    @staticmethod
    def decode_event(msg_type: MessageType, payload: bytes) -> Dict:
        """Single-event decode (slow path / tests). Bulk ingest uses
        decode_event_frames_to_columns instead."""
        tlen = payload[0]
        token = payload[1:1 + tlen].decode()
        pos = 1 + tlen
        (ts,) = struct.unpack_from("<q", payload, pos)
        pos += 8
        out: Dict = {"token": token, "ts_ms": ts}
        if msg_type == MessageType.MEASUREMENT:
            nlen = payload[pos]
            pos += 1
            out["name"] = payload[pos:pos + nlen].decode()
            pos += nlen
            (out["value"],) = struct.unpack_from("<f", payload, pos)
        elif msg_type == MessageType.LOCATION:
            out["lat"], out["lon"], out["elevation"] = struct.unpack_from(
                "<fff", payload, pos)
        elif msg_type == MessageType.ALERT:
            alen = payload[pos]
            pos += 1
            out["type"] = payload[pos:pos + alen].decode()
            pos += alen
            out["level"] = payload[pos]
            pos += 1
            (mlen,) = struct.unpack_from("<H", payload, pos)
            pos += 2
            out["message"] = payload[pos:pos + mlen].decode()
        else:
            raise WireError(f"not a hot event type: {msg_type}")
        return out

    # -- control messages: msgpack maps ------------------------------------
    @staticmethod
    def encode_register(token: str, device_type_token: str,
                        area_token: str = "", customer_token: str = "",
                        metadata: Optional[Dict[str, str]] = None) -> bytes:
        return msgpack.packb({
            "token": token, "deviceType": device_type_token,
            "area": area_token, "customer": customer_token,
            "metadata": metadata or {}}, use_bin_type=True)

    @staticmethod
    def encode_register_ack(token: str, status: str,
                            reason: str = "") -> bytes:
        # status mirrors RegistrationAckState: NEW_REGISTRATION,
        # ALREADY_REGISTERED, REGISTRATION_ERROR (sitewhere.proto:36-47)
        return msgpack.packb({"token": token, "status": status,
                              "reason": reason}, use_bin_type=True)

    @staticmethod
    def encode_command(token: str, command: str,
                       parameters: Optional[Dict[str, str]] = None,
                       invocation_id: str = "") -> bytes:
        return msgpack.packb({
            "token": token, "command": command,
            "parameters": parameters or {},
            "invocationId": invocation_id}, use_bin_type=True)

    @staticmethod
    def encode_command_response(token: str, invocation_id: str,
                                response: str) -> bytes:
        return msgpack.packb({"token": token, "invocationId": invocation_id,
                              "response": response}, use_bin_type=True)

    @staticmethod
    def encode_ack(token: str, message_id: str, response: str = "") -> bytes:
        return msgpack.packb({"token": token, "messageId": message_id,
                              "response": response}, use_bin_type=True)

    @staticmethod
    def encode_stream_data(token: str, stream_id: str, sequence: int,
                           data: bytes) -> bytes:
        return msgpack.packb({"token": token, "streamId": stream_id,
                              "sequence": sequence, "data": data},
                             use_bin_type=True)

    @staticmethod
    def decode_control(payload: bytes) -> Dict:
        return msgpack.unpackb(payload, raw=False)


def decode_event_frames_to_columns(frames: List[Tuple[MessageType, bytes]]
                                   ) -> Dict[str, np.ndarray]:
    """Bulk decode of hot-event frames into SoA columns (tokens stay a
    Python list for interning). This is the Python reference implementation
    of the native C++ decoder's contract: same input layout, same outputs.

    Non-hot frames are skipped (callers route them separately)."""
    hot = [(t, p) for t, p in frames if t in HOT_TYPES]
    n = len(hot)
    tokens: List[str] = [""] * n
    event_type = np.zeros(n, np.int32)
    ts = np.zeros(n, np.int64)
    names: List[str] = [""] * n
    value = np.zeros(n, np.float32)
    lat = np.zeros(n, np.float32)
    lon = np.zeros(n, np.float32)
    elevation = np.zeros(n, np.float32)
    alert_types: List[str] = [""] * n
    alert_level = np.zeros(n, np.int32)
    for i, (mtype, payload) in enumerate(hot):
        ev = WireCodec.decode_event(mtype, payload)
        tokens[i] = ev["token"]
        ts[i] = ev["ts_ms"]
        if mtype == MessageType.MEASUREMENT:
            event_type[i] = 0  # DeviceEventType.MEASUREMENT
            names[i] = ev["name"]
            value[i] = ev["value"]
        elif mtype == MessageType.LOCATION:
            event_type[i] = 1  # DeviceEventType.LOCATION
            lat[i], lon[i] = ev["lat"], ev["lon"]
            elevation[i] = ev["elevation"]
        else:
            event_type[i] = 2  # DeviceEventType.ALERT
            alert_types[i] = ev["type"]
            alert_level[i] = ev["level"]
    return {
        "tokens": tokens, "event_type": event_type, "ts_ms": ts,
        "names": names, "value": value, "lat": lat, "lon": lon,
        "elevation": elevation, "alert_types": alert_types,
        "alert_level": alert_level,
    }
