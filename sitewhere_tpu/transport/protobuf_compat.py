"""Reference device-SDK wire compatibility: the `sitewhere.proto` protocol.

A fleet of existing SiteWhere devices speaks the protobuf protocol defined in
the reference's sitewhere-communication module
(src/main/proto/sitewhere.proto:6-133): every payload is a varint-delimited
`SiteWhere.Header` (command + optional originator) followed by one
varint-delimited body message, decoded by ProtobufDeviceEventDecoder.java and
answered through per-device-type messages built dynamically by
ProtobufMessageBuilder.java / ProtobufSpecificationBuilder.java.

This module implements that wire format with a hand-rolled proto2 codec (no
protoc, no generated classes — the schema is tiny and frozen):

- `ProtobufCompatDecoder` — drop-in `sources.decoders.Decoder` for payloads
  produced by reference device SDKs (registration, acknowledge, measurements,
  location, alert, stream create/data/request).
- device->cloud `encode_*` helpers — a Python device SDK speaking the same
  bytes (also the test vectors: round-tripped against google.protobuf
  dynamic messages in tests/test_protobuf_compat.py).
- `encode_registration_ack` / `encode_device_stream_ack` — the cloud->device
  system messages (Device.Command in sitewhere.proto:111-147).
- `ProtobufSpecCommandEncoder` — the ProtobufMessageBuilder role: encodes a
  custom command invocation against the *device type's* dynamic schema
  (commands enum numbered by list order, per-command message with fields
  numbered by parameter order, typed per ParameterType).

Wire-format notes (proto2): varints little-endian 7-bit groups; field tag =
(field_number << 3) | wire_type; doubles/fixed64 are wire type 1 (8 bytes
LE); strings/bytes/sub-messages are wire type 2 (varint length + payload);
`parseDelimitedFrom` framing is a varint byte-length prefix per message.
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sitewhere_tpu.model.device import DeviceCommand, ParameterType
from sitewhere_tpu.model.event import (
    AlertLevel, AlertSource, DeviceAlert, DeviceCommandResponse,
    DeviceEventBatch, DeviceLocation, DeviceMeasurement,
    DeviceRegistrationRequest, DeviceStreamData)

# SiteWhere.Command (device -> cloud), sitewhere.proto:72-81
SEND_REGISTRATION = 1
SEND_ACKNOWLEDGEMENT = 2
SEND_DEVICE_LOCATION = 3
SEND_DEVICE_ALERT = 4
SEND_DEVICE_MEASUREMENTS = 5
SEND_DEVICE_STREAM = 6
SEND_DEVICE_STREAM_DATA = 7
REQUEST_DEVICE_STREAM_DATA = 8

# Device.Command (cloud -> device), sitewhere.proto:114-118
ACK_REGISTRATION = 1
ACK_DEVICE_STREAM = 2
RECEIVE_DEVICE_STREAM_DATA = 3


class RegistrationAckState(enum.IntEnum):
    """Device.RegistrationAckState, sitewhere.proto:129."""

    NEW_REGISTRATION = 1
    ALREADY_REGISTERED = 2
    REGISTRATION_ERROR = 3


class RegistrationAckError(enum.IntEnum):
    """Device.RegistrationAckError, sitewhere.proto:130."""

    INVALID_SPECIFICATION = 1
    SITE_TOKEN_REQUIRED = 2
    NEW_DEVICES_NOT_ALLOWED = 3


class ProtobufCompatError(Exception):
    """Malformed sitewhere.proto payload."""


# ---------------------------------------------------------------------------
# proto2 wire primitives
# ---------------------------------------------------------------------------

def _write_varint(value: int) -> bytes:
    if value < 0:  # proto2 int32/int64 negatives ride as 10-byte varints
        value += 1 << 64
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if off >= len(buf):
            raise ProtobufCompatError("truncated varint")
        byte = buf[off]
        off += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, off
        shift += 7
        if shift > 63:
            raise ProtobufCompatError("varint too long")


def _zigzag(value: int) -> int:
    return (value << 1) ^ (value >> 63)


def _unzigzag(value: int) -> int:
    return (value >> 1) ^ -(value & 1)


def _tag(field_number: int, wire_type: int) -> bytes:
    return _write_varint((field_number << 3) | wire_type)


class _Writer:
    """Accumulates one message's fields in write order."""

    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def varint(self, num: int, value: int) -> "_Writer":
        self._parts.append(_tag(num, 0) + _write_varint(value))
        return self

    def bool(self, num: int, value: bool) -> "_Writer":
        return self.varint(num, 1 if value else 0)

    def sint(self, num: int, value: int) -> "_Writer":
        return self.varint(num, _zigzag(value))

    def fixed64(self, num: int, value: int) -> "_Writer":
        self._parts.append(_tag(num, 1) + struct.pack("<Q", value & (2**64 - 1)))
        return self

    def sfixed64(self, num: int, value: int) -> "_Writer":
        self._parts.append(_tag(num, 1) + struct.pack("<q", value))
        return self

    def double(self, num: int, value: float) -> "_Writer":
        self._parts.append(_tag(num, 1) + struct.pack("<d", value))
        return self

    def fixed32(self, num: int, value: int) -> "_Writer":
        self._parts.append(_tag(num, 5) + struct.pack("<I", value & (2**32 - 1)))
        return self

    def sfixed32(self, num: int, value: int) -> "_Writer":
        self._parts.append(_tag(num, 5) + struct.pack("<i", value))
        return self

    def float(self, num: int, value: float) -> "_Writer":
        self._parts.append(_tag(num, 5) + struct.pack("<f", value))
        return self

    def bytes(self, num: int, value: bytes) -> "_Writer":
        self._parts.append(_tag(num, 2) + _write_varint(len(value)) + value)
        return self

    def string(self, num: int, value: str) -> "_Writer":
        return self.bytes(num, value.encode("utf-8"))

    def message(self, num: int, sub: "_Writer") -> "_Writer":
        return self.bytes(num, sub.build())

    def build(self) -> bytes:
        return b"".join(self._parts)

    def delimited(self) -> bytes:
        body = self.build()
        return _write_varint(len(body)) + body


@dataclass
class _Fields:
    """Parsed message: field number -> list of raw values in wire order.
    wire type 0 -> int, 1 -> 8 raw bytes, 2 -> bytes, 5 -> 4 raw bytes."""

    raw: Dict[int, List[Any]] = field(default_factory=dict)

    @classmethod
    def parse(cls, buf: bytes) -> "_Fields":
        fields = cls()
        off = 0
        while off < len(buf):
            key, off = _read_varint(buf, off)
            num, wt = key >> 3, key & 7
            if wt == 0:
                value, off = _read_varint(buf, off)
            elif wt == 1:
                value, off = buf[off:off + 8], off + 8
            elif wt == 2:
                length, off = _read_varint(buf, off)
                value, off = buf[off:off + length], off + length
                if len(value) != length:
                    raise ProtobufCompatError("truncated length-delimited")
            elif wt == 5:
                value, off = buf[off:off + 4], off + 4
            else:
                raise ProtobufCompatError(f"unsupported wire type {wt}")
            if off > len(buf):
                raise ProtobufCompatError("truncated field")
            fields.raw.setdefault(num, []).append(value)
        return fields

    # typed getters (last-value-wins for scalars, as protobuf specifies)
    def int(self, num: int, default: int = 0) -> int:
        values = self.raw.get(num)
        if not values:
            return default
        value = int(values[-1])
        if value >= 1 << 63:  # proto2 int32/int64 negatives are 64-bit
            value -= 1 << 64  # two's-complement varints; restore the sign
        return value

    def str(self, num: int, default: str = "") -> str:
        values = self.raw.get(num)
        return values[-1].decode("utf-8") if values else default

    def bytes(self, num: int, default: bytes = b"") -> bytes:
        values = self.raw.get(num)
        return values[-1] if values else default

    def double(self, num: int, default: float = 0.0) -> float:
        values = self.raw.get(num)
        return struct.unpack("<d", values[-1])[0] if values else default

    def fixed64(self, num: int, default: int = 0) -> int:
        values = self.raw.get(num)
        return struct.unpack("<Q", values[-1])[0] if values else default

    def bool(self, num: int, default: bool = False) -> bool:
        values = self.raw.get(num)
        return bool(int(values[-1])) if values else default

    def messages(self, num: int) -> List["_Fields"]:
        return [_Fields.parse(v) for v in self.raw.get(num, [])]

    def has(self, num: int) -> bool:
        return num in self.raw


def read_delimited(buf: bytes, off: int = 0) -> Tuple[bytes, int]:
    """One `parseDelimitedFrom` frame: varint length + that many bytes."""
    length, off = _read_varint(buf, off)
    end = off + length
    if end > len(buf):
        raise ProtobufCompatError("truncated delimited message")
    return buf[off:end], end


def _metadata(fields: _Fields, num: int) -> Dict[str, str]:
    """repeated Model.Metadata {1: name, 2: value} (sitewhere.proto:9-12)."""
    return {m.str(1): m.str(2) for m in fields.messages(num)}


def _meta_writer(w: _Writer, num: int, metadata: Optional[Dict[str, str]]
                 ) -> None:
    for name, value in (metadata or {}).items():
        w.message(num, _Writer().string(1, name).string(2, value))


# ---------------------------------------------------------------------------
# device -> cloud: encode (the SDK side; also the decoder's test vectors)
# ---------------------------------------------------------------------------

def _with_header(command: int, body: _Writer,
                 originator: Optional[str] = None) -> bytes:
    header = _Writer().varint(1, command)
    if originator:
        header.string(2, originator)
    return header.delimited() + body.delimited()


def encode_registration(hardware_id: str, device_type_token: str,
                        metadata: Optional[Dict[str, str]] = None,
                        area_token: Optional[str] = None,
                        originator: Optional[str] = None) -> bytes:
    """SiteWhere.RegisterDevice (sitewhere.proto:90-95)."""
    w = _Writer().string(1, hardware_id).string(2, device_type_token)
    _meta_writer(w, 3, metadata)
    if area_token:
        w.string(4, area_token)
    return _with_header(SEND_REGISTRATION, w, originator)


def encode_acknowledge(hardware_id: str, message: str = "",
                       originator: Optional[str] = None) -> bytes:
    """SiteWhere.Acknowledge (sitewhere.proto:98-101)."""
    w = _Writer().string(1, hardware_id)
    if message:
        w.string(2, message)
    return _with_header(SEND_ACKNOWLEDGEMENT, w, originator)


def encode_measurements(hardware_id: str,
                        measurements: Sequence[Tuple[str, float]],
                        event_date_ms: Optional[int] = None,
                        metadata: Optional[Dict[str, str]] = None,
                        update_state: Optional[bool] = None,
                        originator: Optional[str] = None) -> bytes:
    """Model.DeviceMeasurements (sitewhere.proto:42-48)."""
    w = _Writer().string(1, hardware_id)
    for name, value in measurements:
        w.message(2, _Writer().string(1, name).double(2, float(value)))
    if event_date_ms is not None:
        w.fixed64(3, event_date_ms)
    _meta_writer(w, 4, metadata)
    if update_state is not None:
        w.bool(5, update_state)
    return _with_header(SEND_DEVICE_MEASUREMENTS, w, originator)


def encode_location(hardware_id: str, latitude: float, longitude: float,
                    elevation: Optional[float] = None,
                    event_date_ms: Optional[int] = None,
                    metadata: Optional[Dict[str, str]] = None,
                    update_state: Optional[bool] = None,
                    originator: Optional[str] = None) -> bytes:
    """Model.DeviceLocation (sitewhere.proto:15-23)."""
    w = (_Writer().string(1, hardware_id)
         .double(2, latitude).double(3, longitude))
    if elevation is not None:
        w.double(4, elevation)
    if event_date_ms is not None:
        w.fixed64(5, event_date_ms)
    _meta_writer(w, 6, metadata)
    if update_state is not None:
        w.bool(7, update_state)
    return _with_header(SEND_DEVICE_LOCATION, w, originator)


def encode_alert(hardware_id: str, alert_type: str, alert_message: str,
                 event_date_ms: Optional[int] = None,
                 metadata: Optional[Dict[str, str]] = None,
                 update_state: Optional[bool] = None,
                 originator: Optional[str] = None) -> bytes:
    """Model.DeviceAlert (sitewhere.proto:26-33)."""
    w = (_Writer().string(1, hardware_id).string(2, alert_type)
         .string(3, alert_message))
    if event_date_ms is not None:
        w.fixed64(4, event_date_ms)
    _meta_writer(w, 5, metadata)
    if update_state is not None:
        w.bool(6, update_state)
    return _with_header(SEND_DEVICE_ALERT, w, originator)


def encode_stream_create(hardware_id: str, stream_id: str, content_type: str,
                         metadata: Optional[Dict[str, str]] = None,
                         originator: Optional[str] = None) -> bytes:
    """Model.DeviceStream (sitewhere.proto:51-56)."""
    w = (_Writer().string(1, hardware_id).string(2, stream_id)
         .string(3, content_type))
    _meta_writer(w, 4, metadata)
    return _with_header(SEND_DEVICE_STREAM, w, originator)


def encode_stream_data(hardware_id: str, stream_id: str,
                       sequence_number: int, data: bytes,
                       event_date_ms: Optional[int] = None,
                       originator: Optional[str] = None) -> bytes:
    """Model.DeviceStreamData (sitewhere.proto:59-66)."""
    w = (_Writer().string(1, hardware_id).string(2, stream_id)
         .fixed64(3, sequence_number).bytes(4, data))
    if event_date_ms is not None:
        w.fixed64(5, event_date_ms)
    return _with_header(SEND_DEVICE_STREAM_DATA, w, originator)


def encode_stream_data_request(hardware_id: str, stream_id: str,
                               sequence_number: int,
                               originator: Optional[str] = None) -> bytes:
    """SiteWhere.DeviceStreamDataRequest (sitewhere.proto:104-108)."""
    w = (_Writer().string(1, hardware_id).string(2, stream_id)
         .fixed64(3, sequence_number))
    return _with_header(REQUEST_DEVICE_STREAM_DATA, w, originator)


# ---------------------------------------------------------------------------
# cloud -> device: decode in the device SDK / tests
# ---------------------------------------------------------------------------

@dataclass
class DeviceStreamCreateRequest:
    """Decoded SEND_DEVICE_STREAM (the reference maps it to
    DeviceStreamCreateRequest)."""

    device_token: str = ""
    stream_id: str = ""
    content_type: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class StreamDataRequest:
    """Decoded REQUEST_DEVICE_STREAM_DATA (SendDeviceStreamDataRequest)."""

    device_token: str = ""
    stream_id: str = ""
    sequence_number: int = 0


class ProtobufCompatDecoder:
    """`sources.decoders.Decoder` for reference-SDK payloads.

    Mirrors ProtobufDeviceEventDecoder.java's mapping: measurements fan out
    per Measurement entry; a missing eventDate means "now" (left as 0 here —
    the inbound pipeline stamps receive time); SEND_ACKNOWLEDGEMENT becomes
    a command response whose originating id is the header originator.
    """

    def decode(self, payload: bytes,
               metadata: Optional[Dict[str, str]] = None):
        from sitewhere_tpu.sources.decoders import DecodeError, DecodedRequest

        try:
            return self._decode(payload)
        except (ProtobufCompatError, UnicodeDecodeError,
                struct.error) as exc:
            # UnicodeDecodeError: corrupt bytes in a string field;
            # struct.error: short fixed32/64 slice. Both must route to the
            # failed-decode topic like any other undecodable payload.
            raise DecodeError(f"bad sitewhere.proto payload: {exc}") from exc

    def _decode(self, payload: bytes):
        from sitewhere_tpu.sources.decoders import DecodedRequest

        header_buf, off = read_delimited(payload)
        header = _Fields.parse(header_buf)
        command = header.int(1)
        originator = header.str(2)
        body_buf, _ = read_delimited(payload, off)
        body = _Fields.parse(body_buf)
        token = body.str(1)
        if not token:
            raise ProtobufCompatError("missing hardwareId")
        meta = {}
        out: List[DecodedRequest] = []

        if command == SEND_REGISTRATION:
            out.append(DecodedRequest(token, DeviceRegistrationRequest(
                device_token=token, device_type_token=body.str(2),
                area_token=body.str(4), metadata=_metadata(body, 3))))
        elif command == SEND_ACKNOWLEDGEMENT:
            out.append(DecodedRequest(token, DeviceCommandResponse(
                originating_event_id=originator, response=body.str(2))))
        elif command == SEND_DEVICE_MEASUREMENTS:
            batch = DeviceEventBatch(device_token=token)
            meta = _metadata(body, 4)
            for m in body.messages(2):
                batch.measurements.append(DeviceMeasurement(
                    name=m.str(1), value=m.double(2),
                    event_date=body.fixed64(3), metadata=dict(meta)))
            out.append(DecodedRequest(token, batch, metadata=meta))
        elif command == SEND_DEVICE_LOCATION:
            batch = DeviceEventBatch(device_token=token)
            meta = _metadata(body, 6)
            batch.locations.append(DeviceLocation(
                latitude=body.double(2), longitude=body.double(3),
                elevation=body.double(4), event_date=body.fixed64(5),
                metadata=dict(meta)))
            out.append(DecodedRequest(token, batch, metadata=meta))
        elif command == SEND_DEVICE_ALERT:
            batch = DeviceEventBatch(device_token=token)
            meta = _metadata(body, 5)
            batch.alerts.append(DeviceAlert(
                type=body.str(2), message=body.str(3),
                level=AlertLevel.INFO, source=AlertSource.DEVICE,
                event_date=body.fixed64(4), metadata=dict(meta)))
            out.append(DecodedRequest(token, batch, metadata=meta))
        elif command == SEND_DEVICE_STREAM:
            out.append(DecodedRequest(token, DeviceStreamCreateRequest(
                device_token=token, stream_id=body.str(2),
                content_type=body.str(3), metadata=_metadata(body, 4))))
        elif command == SEND_DEVICE_STREAM_DATA:
            out.append(DecodedRequest(token, DeviceStreamData(
                stream_id=body.str(2), sequence_number=body.fixed64(3),
                data=body.bytes(4), event_date=body.fixed64(5))))
        elif command == REQUEST_DEVICE_STREAM_DATA:
            out.append(DecodedRequest(token, StreamDataRequest(
                device_token=token, stream_id=body.str(2),
                sequence_number=body.fixed64(3))))
        else:
            raise ProtobufCompatError(f"unknown command {command}")
        return out


# ---------------------------------------------------------------------------
# cloud -> device system messages (Device.Command)
# ---------------------------------------------------------------------------

def _device_header(command: int, originator: Optional[str] = None,
                   nested_path: Optional[str] = None,
                   nested_spec: Optional[str] = None) -> _Writer:
    header = _Writer().varint(1, command)
    if originator:
        header.string(2, originator)
    if nested_path:
        header.string(3, nested_path)
    if nested_spec:
        header.string(4, nested_spec)
    return header


def encode_registration_ack(state: RegistrationAckState,
                            error_type: Optional[RegistrationAckError] = None,
                            error_message: str = "",
                            originator: Optional[str] = None) -> bytes:
    """Device.RegistrationAck (sitewhere.proto:133-137), delimited after a
    Device.Header — what the reference's RegistrationManager sends back."""
    ack = _Writer().varint(1, int(state))
    if error_type is not None:
        ack.varint(2, int(error_type))
    if error_message:
        ack.string(3, error_message)
    return (_device_header(ACK_REGISTRATION, originator).delimited()
            + ack.delimited())


def encode_device_stream_ack(stream_id: str, state: int,
                             originator: Optional[str] = None) -> bytes:
    """Device.DeviceStreamAck (sitewhere.proto:143-146)."""
    ack = _Writer().string(1, stream_id).varint(2, state)
    return (_device_header(ACK_DEVICE_STREAM, originator).delimited()
            + ack.delimited())


def decode_device_payload(payload: bytes) -> Tuple[int, str, _Fields]:
    """Device-side helper (and test hook): returns (command, originator,
    parsed body fields) of a cloud->device payload."""
    header_buf, off = read_delimited(payload)
    header = _Fields.parse(header_buf)
    body_buf, _ = read_delimited(payload, off)
    return header.int(1), header.str(2), _Fields.parse(body_buf)


# ---------------------------------------------------------------------------
# per-device-type command encoding (ProtobufMessageBuilder role)
# ---------------------------------------------------------------------------

def _encode_parameter(w: _Writer, num: int, ptype: ParameterType,
                      value: str) -> None:
    """Encode one string-coerced parameter with the declared proto2 type —
    the dynamic-field mapping of ProtobufSpecificationBuilder.getType."""
    if ptype == ParameterType.DOUBLE:
        w.double(num, float(value))
    elif ptype == ParameterType.FLOAT:
        w.float(num, float(value))
    elif ptype in (ParameterType.INT32, ParameterType.INT64,
                   ParameterType.UINT32, ParameterType.UINT64):
        w.varint(num, int(value))
    elif ptype in (ParameterType.SINT32, ParameterType.SINT64):
        w.sint(num, int(value))
    elif ptype == ParameterType.FIXED32:
        w.fixed32(num, int(value))
    elif ptype == ParameterType.FIXED64:
        w.fixed64(num, int(value))
    elif ptype == ParameterType.SFIXED32:
        w.sfixed32(num, int(value))
    elif ptype == ParameterType.SFIXED64:
        w.sfixed64(num, int(value))
    elif ptype == ParameterType.BOOL:
        w.bool(num, value.lower() in ("1", "true", "yes", "on"))
    elif ptype == ParameterType.BYTES:
        w.bytes(num, bytes.fromhex(value))
    else:  # STRING
        w.string(num, value)


class ProtobufSpecCommandEncoder:
    """Command encoder speaking the per-device-type dynamic protobuf schema.

    ProtobufMessageBuilder.java builds, per device type: a `Command` enum
    whose values number the type's commands 1..N in listing order, a header
    message {1: command enum, 2: originator, 3: nestedPath, 4: nestedSpec},
    and one message per command whose fields number the command's parameters
    1..K in declaration order. The payload is delimited(header) +
    delimited(command message). Reproducing the numbering scheme (not the
    DynamicMessage machinery) is what keeps reference devices compatible.
    """

    def __init__(self, registry):
        self.registry = registry

    def _command_number(self, device, command: DeviceCommand) -> int:
        dtype = self.registry.device_types.get(device.device_type_id)
        if dtype is None:
            raise ValueError(f"device {device.token} has no device type")
        commands = self.registry.list_device_commands(dtype.token).results
        for i, candidate in enumerate(commands, start=1):
            if candidate.name == command.name:
                return i
        raise ValueError(
            f"command {command.name} not declared on type {dtype.token}")

    def encode(self, execution, device, assignment, nesting=None) -> bytes:
        number = self._command_number(device, execution.command)
        nested_path = nested_spec = None
        if nesting is not None and nesting.nested is not None:
            # gateway-framed message addressing a composite child: the
            # header carries the element-schema path and the nested
            # device's TYPE token (ProtobufMessageBuilder.java:76-82
            # setting nestedPath + nestedSpec from the mapping)
            nested_path = nesting.path
            nested_type = self.registry.device_types.get(
                nesting.nested.device_type_id)
            nested_spec = nested_type.token if nested_type else None
        header = _device_header(number,
                                originator=execution.invocation.id or None,
                                nested_path=nested_path,
                                nested_spec=nested_spec)
        body = _Writer()
        for num, parameter in enumerate(execution.command.parameters,
                                        start=1):
            value = execution.parameters.get(parameter.name)
            if value is None:
                continue
            _encode_parameter(body, num, parameter.type, value)
        return header.delimited() + body.delimited()

    def encode_system(self, command, device) -> bytes:
        """System messages for protobuf-SDK devices: re-encode the wire
        REGISTER_ACK payload as a Device.RegistrationAck."""
        from sitewhere_tpu.transport.wire import MessageType, WireCodec

        if command.message_type == MessageType.REGISTER_ACK:
            doc = WireCodec.decode_control(command.payload)
            state = RegistrationAckState[doc.get(
                "status", "REGISTRATION_ERROR")]
            error = (RegistrationAckError.INVALID_SPECIFICATION
                     if state == RegistrationAckState.REGISTRATION_ERROR
                     else None)
            return encode_registration_ack(state, error_type=error,
                                           error_message=doc.get("reason", ""))
        raise ValueError(
            f"no sitewhere.proto mapping for system message "
            f"{MessageType(command.message_type).name}")
