"""Minimal CoAP (RFC 7252) UDP server for constrained devices.

Reference: service-event-sources coap/CoapServerEventReceiver.java hosts a
Californium CoAP server; devices POST JSON/binary event payloads to
resource paths. Here: an asyncio DatagramProtocol parsing the CoAP binary
header/options, dispatching POST/PUT to a handler, and answering with a
piggybacked ACK (2.04 Changed / 4.xx on error). Confirmable (CON) and
non-confirmable (NON) requests supported; no observe/blockwise (the
reference doesn't use them for ingest either).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Callable, Optional, Tuple

# method / response codes (class.detail)
GET, POST, PUT, DELETE = 1, 2, 3, 4
CHANGED = (2 << 5) | 4      # 2.04
BAD_REQUEST = (4 << 5) | 0  # 4.00
SERVER_ERROR = (5 << 5) | 0  # 5.00
TYPE_CON, TYPE_NON, TYPE_ACK, TYPE_RST = 0, 1, 2, 3
OPT_URI_PATH = 11


def _parse_options(data: bytes, pos: int) -> Tuple[list, int]:
    """Returns ([(number, value)], payload_start)."""
    options = []
    number = 0
    while pos < len(data):
        byte = data[pos]
        if byte == 0xFF:
            return options, pos + 1
        delta, length = byte >> 4, byte & 0x0F
        pos += 1
        if delta == 13:
            delta = data[pos] + 13
            pos += 1
        elif delta == 14:
            delta = struct.unpack_from("!H", data, pos)[0] + 269
            pos += 2
        if length == 13:
            length = data[pos] + 13
            pos += 1
        elif length == 14:
            length = struct.unpack_from("!H", data, pos)[0] + 269
            pos += 2
        number += delta
        options.append((number, data[pos:pos + length]))
        pos += length
    return options, len(data)


def parse_message(data: bytes):
    """-> (type, code, message_id, token, path, payload) or None if malformed."""
    if len(data) < 4:
        return None
    b0, code, mid = data[0], data[1], struct.unpack_from("!H", data, 2)[0]
    version, mtype, tkl = b0 >> 6, (b0 >> 4) & 0x03, b0 & 0x0F
    if version != 1 or tkl > 8:
        return None
    token = data[4:4 + tkl]
    options, payload_start = _parse_options(data, 4 + tkl)
    path = "/".join(v.decode("utf-8", "replace")
                    for n, v in options if n == OPT_URI_PATH)
    return mtype, code, mid, token, path, data[payload_start:]


def build_response(mtype: int, code: int, mid: int, token: bytes,
                   payload: bytes = b"") -> bytes:
    head = bytes([(1 << 6) | (mtype << 4) | len(token), code]) + \
        struct.pack("!H", mid) + token
    return head + (b"\xff" + payload if payload else b"")


def _encode_option(delta: int, value: bytes) -> bytes:
    def nibble(n: int) -> Tuple[int, bytes]:
        if n < 13:
            return n, b""
        if n < 269:
            return 13, bytes([n - 13])
        return 14, struct.pack("!H", n - 269)

    dn, dext = nibble(delta)
    ln, lext = nibble(len(value))
    return bytes([(dn << 4) | ln]) + dext + lext + value


def build_request(mtype: int, code: int, mid: int, path: str,
                  payload: bytes = b"", token: bytes = b"") -> bytes:
    """Client-side message builder (the piece Californium provides the
    reference's CoapCommandDeliveryProvider)."""
    head = bytes([(1 << 6) | (mtype << 4) | len(token), code]) + \
        struct.pack("!H", mid) + token
    options = b""
    previous = 0
    for segment in path.strip("/").split("/"):
        if not segment:
            continue
        options += _encode_option(OPT_URI_PATH - previous,
                                  segment.encode("utf-8"))
        previous = OPT_URI_PATH
    return head + options + (b"\xff" + payload if payload else b"")


class CoapClient:
    """Minimal CoAP client: POST a payload to host:port/path. CON requests
    wait for the piggybacked ACK; NON requests are fire-and-forget."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._mid = 0

    async def post(self, path: str, payload: bytes, confirmable: bool = True,
                   timeout_s: float = 5.0) -> Optional[int]:
        """Returns the response code for CON, None for NON."""
        self._mid = (self._mid + 1) & 0xFFFF
        mtype = TYPE_CON if confirmable else TYPE_NON
        message = build_request(mtype, POST, self._mid, path, payload)
        loop = asyncio.get_running_loop()
        done: asyncio.Future = loop.create_future()
        mid = self._mid

        class _ClientProtocol(asyncio.DatagramProtocol):
            def connection_made(self, transport) -> None:
                transport.sendto(message)

            def datagram_received(self, data: bytes, addr) -> None:
                parsed = parse_message(data)
                if parsed and parsed[2] == mid and not done.done():
                    done.set_result(parsed[1])

        transport, _ = await loop.create_datagram_endpoint(
            _ClientProtocol, remote_addr=(self.host, self.port))
        try:
            if not confirmable:
                return None
            return await asyncio.wait_for(done, timeout_s)
        finally:
            transport.close()


class CoapServer:
    """`handler(path, payload, method) -> response payload or None` runs for
    every POST/PUT; exceptions map to 5.00."""

    def __init__(self, handler: Callable[[str, bytes, int], Optional[bytes]],
                 host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._transport: Optional[asyncio.DatagramTransport] = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _Protocol(self), local_addr=(self.host, self.port))
        self.port = self._transport.get_extra_info("sockname")[1]

    async def stop(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None


class _Protocol(asyncio.DatagramProtocol):
    def __init__(self, server: CoapServer):
        self.server = server
        self.transport: Optional[asyncio.DatagramTransport] = None

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        parsed = parse_message(data)
        if parsed is None:
            return
        mtype, code, mid, token, path, payload = parsed
        if mtype not in (TYPE_CON, TYPE_NON):
            return
        if code not in (POST, PUT):
            self._reply(mtype, BAD_REQUEST, mid, token, addr)
            return
        try:
            result = self.server.handler(path, payload, code)
            self._reply(mtype, CHANGED, mid, token, addr, result or b"")
        except Exception:
            self._reply(mtype, SERVER_ERROR, mid, token, addr)

    def _reply(self, req_type: int, code: int, mid: int, token: bytes,
               addr, payload: bytes = b"") -> None:
        if req_type == TYPE_CON:  # piggybacked ACK
            self.transport.sendto(
                build_response(TYPE_ACK, code, mid, token, payload), addr)
        # NON requests get no response (fire-and-forget ingest)
