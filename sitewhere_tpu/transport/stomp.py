"""In-process STOMP 1.2 broker + client (asyncio, from scratch).

Reference: service-event-sources hosts an in-JVM ActiveMQ broker and
consumes device events from one of its queues
(activemq/ActiveMQBrokerEventReceiver.java) — devices connect TO the
platform's own broker; no external middleware. The rebuild's equivalent
embeds this broker the same way the in-proc MQTT broker
(transport/mqtt.py) fills the HiveMQ/Mosquitto slot: a minimal,
dependency-free server speaking the real public protocol, so any STOMP
client library (stomp.py, stompjs, ActiveMQ's own clients) can publish
events at it.

Protocol subset (STOMP 1.2, https://stomp.github.io/): CONNECT/STOMP ->
CONNECTED; SEND fans out to SUBSCRIBE'd destinations as MESSAGE frames;
UNSUBSCRIBE, DISCONNECT, and `receipt` headers are honored; ACK/NACK are
accepted and ignored (subscriptions are ack:auto); heart-beats are
negotiated off (0,0). Frames: COMMAND line, header lines, blank line,
body, NUL. Bodies honor content-length (binary-safe) and fall back to
read-to-NUL.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

LOGGER = logging.getLogger("sitewhere.stomp")

_NUL = b"\x00"
_EOL = b"\n"
# hard caps — the client controls content-length and the header stream,
# and readexactly() is NOT bounded by the stream limit, so an
# unauthenticated socket could otherwise make the broker buffer
# arbitrary memory (real brokers enforce a max frame size the same way).
# Individual header LINES are already bounded by the asyncio stream
# limit (readline); the count cap bounds the whole header block.
MAX_FRAME_BYTES = 4 * 1024 * 1024
MAX_HEADERS = 128


class StompProtocolError(Exception):
    pass


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\r", "\\r")
            .replace("\n", "\\n").replace(":", "\\c"))


def _unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            mapped = {"\\": "\\", "r": "\r", "n": "\n", "c": ":"}.get(nxt)
            if mapped is None:
                raise StompProtocolError(f"bad escape \\{nxt}")
            out.append(mapped)
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def encode_frame(command: str, headers: Dict[str, str],
                 body: bytes = b"") -> bytes:
    lines = [command.encode("ascii")]
    hdrs = dict(headers)
    if body:
        hdrs.setdefault("content-length", str(len(body)))
    for key, value in hdrs.items():
        lines.append(f"{_escape(key)}:{_escape(str(value))}"
                     .encode("utf-8"))
    return _EOL.join(lines) + _EOL + _EOL + body + _NUL


async def read_frame(reader: asyncio.StreamReader
                     ) -> Optional[Tuple[str, Dict[str, str], bytes]]:
    """One frame, or None at EOF. Tolerates heart-beat/blank lines
    between frames."""
    # command line (skip EOLs used as heart-beats)
    while True:
        line = await reader.readline()
        if not line:
            return None
        stripped = line.strip(b"\r\n")
        if stripped:
            break
    command = stripped.decode("utf-8")
    headers: Dict[str, str] = {}
    header_lines = 0
    while True:
        line = await reader.readline()
        if not line:
            return None
        stripped = line.rstrip(b"\r\n")
        if not stripped:
            break
        # bound RAW header lines, not the deduplicated dict size: a
        # stream repeating one header forever would otherwise never trip
        # the cap (setdefault keeps len(headers) at 1) and spin this loop
        # unbounded
        header_lines += 1
        if header_lines > MAX_HEADERS:
            raise StompProtocolError("too many headers")
        key, sep, value = stripped.decode("utf-8").partition(":")
        if not sep:
            raise StompProtocolError(f"malformed header line {line!r}")
        # STOMP 1.2: repeated headers keep the FIRST occurrence
        headers.setdefault(_unescape(key), _unescape(value))
    length = headers.get("content-length")
    if length is not None:
        try:
            nbytes = int(length)
        except ValueError:
            raise StompProtocolError(
                f"bad content-length {length!r}") from None
        if nbytes < 0 or nbytes > MAX_FRAME_BYTES:
            raise StompProtocolError(f"bad content-length {length!r}")
        body = await reader.readexactly(nbytes)
        nul = await reader.readexactly(1)
        if nul != _NUL:
            raise StompProtocolError("frame body not NUL-terminated")
    else:
        try:
            raw = await reader.readuntil(_NUL)
        except asyncio.LimitOverrunError:
            raise StompProtocolError(
                "unframed body exceeds the stream limit; send "
                "content-length") from None
        body = raw[:-1]
    return command, headers, body


class _Subscription:
    def __init__(self, sub_id: str, destination: str, session):
        self.sub_id = sub_id
        self.destination = destination
        self.session = session


class _BrokerSession:
    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.subscriptions: Dict[str, _Subscription] = {}
        self._lock = asyncio.Lock()

    async def send(self, data: bytes) -> None:
        async with self._lock:
            self.writer.write(data)
            await self.writer.drain()


class StompBroker:
    """Embedded STOMP broker (the ActiveMQBrokerEventReceiver's in-JVM
    broker role). Topic semantics: every subscriber of a destination gets
    every message (devices publish telemetry; the platform receiver and
    any debugging consumer can both listen)."""

    # a subscriber that can't drain a frame within this budget is dead
    # weight: drop it rather than let its full TCP buffer stall every
    # publisher to the destination
    SEND_TIMEOUT_S = 10.0

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        # destination -> list of subscriptions
        self._subs: Dict[str, List[_Subscription]] = {}
        self._sessions: set = set()
        self._message_seq = 0

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # server.close() only stops the LISTENER: established device
        # connections must be closed too, or they'd stay attached to a
        # dead broker silently dropping every SEND
        for session in list(self._sessions):
            session.writer.close()
        self._sessions.clear()
        self._subs.clear()

    async def _handle_client(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        session = _BrokerSession(writer)
        self._sessions.add(session)
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                command, headers, body = frame
                if command in ("CONNECT", "STOMP"):
                    await session.send(encode_frame(
                        "CONNECTED", {"version": "1.2",
                                      "heart-beat": "0,0"}))
                elif command == "SEND":
                    await self._on_send(headers, body)
                    await self._maybe_receipt(session, headers)
                elif command == "SUBSCRIBE":
                    self._on_subscribe(session, headers)
                    await self._maybe_receipt(session, headers)
                elif command == "UNSUBSCRIBE":
                    self._on_unsubscribe(session, headers)
                    await self._maybe_receipt(session, headers)
                elif command in ("ACK", "NACK"):
                    pass  # subscriptions are ack:auto
                elif command == "DISCONNECT":
                    await self._maybe_receipt(session, headers)
                    break
                else:
                    await session.send(encode_frame(
                        "ERROR", {"message": f"unsupported {command}"}))
                    break
        except (StompProtocolError, asyncio.IncompleteReadError,
                ConnectionError) as exc:
            LOGGER.debug("stomp session ended: %s", exc)
        finally:
            self._sessions.discard(session)
            for sub in list(session.subscriptions.values()):
                self._drop(sub)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    @staticmethod
    async def _maybe_receipt(session: _BrokerSession,
                             headers: Dict[str, str]) -> None:
        receipt = headers.get("receipt")
        if receipt:
            await session.send(encode_frame("RECEIPT",
                                            {"receipt-id": receipt}))

    def _on_subscribe(self, session: _BrokerSession,
                      headers: Dict[str, str]) -> None:
        sub_id = headers.get("id")
        destination = headers.get("destination")
        if not sub_id or not destination:
            raise StompProtocolError("SUBSCRIBE requires id + destination")
        sub = _Subscription(sub_id, destination, session)
        session.subscriptions[sub_id] = sub
        self._subs.setdefault(destination, []).append(sub)

    def _on_unsubscribe(self, session: _BrokerSession,
                        headers: Dict[str, str]) -> None:
        sub = session.subscriptions.pop(headers.get("id", ""), None)
        if sub is not None:
            self._drop(sub)

    def _drop(self, sub: _Subscription) -> None:
        subs = self._subs.get(sub.destination, [])
        if sub in subs:
            subs.remove(sub)
        if not subs:
            self._subs.pop(sub.destination, None)

    async def _on_send(self, headers: Dict[str, str], body: bytes) -> None:
        destination = headers.get("destination")
        if not destination:
            raise StompProtocolError("SEND requires destination")
        self._message_seq += 1
        for sub in list(self._subs.get(destination, [])):
            frame = encode_frame("MESSAGE", {
                "destination": destination,
                "message-id": str(self._message_seq),
                "subscription": sub.sub_id,
            }, body)
            try:
                await asyncio.wait_for(sub.session.send(frame),
                                       self.SEND_TIMEOUT_S)
            except asyncio.TimeoutError:
                LOGGER.warning(
                    "dropping stalled subscriber %s on %s (write "
                    "exceeded %.0fs)", sub.sub_id, destination,
                    self.SEND_TIMEOUT_S)
                self._drop(sub)
                sub.session.writer.close()
            except (ConnectionError, OSError):
                self._drop(sub)


class StompClient:
    """Minimal STOMP 1.2 client for the embedded broker (tests, in-proc
    consumers, co-located simulators)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._read_task: Optional[asyncio.Task] = None
        self._connected = asyncio.Event()
        self._sub_seq = 0
        self._handlers: Dict[str, Callable[[Dict[str, str], bytes],
                                           Awaitable[None]]] = {}

    async def connect(self, timeout_s: float = 5.0) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._writer.write(encode_frame(
            "CONNECT", {"accept-version": "1.2", "host": self.host,
                        "heart-beat": "0,0"}))
        await self._writer.drain()
        self._read_task = asyncio.ensure_future(self._read_loop())
        try:
            await asyncio.wait_for(self._connected.wait(), timeout_s)
        except asyncio.TimeoutError:
            # no CONNECTED handshake: don't leak the socket + read task
            # (a reconnect loop would accumulate one of each per try)
            self._read_task.cancel()
            self._read_task = None
            self._writer.close()
            self._writer = None
            self._reader = None
            raise

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                command, headers, body = frame
                if command == "CONNECTED":
                    self._connected.set()
                elif command == "MESSAGE":
                    handler = self._handlers.get(
                        headers.get("subscription", ""))
                    if handler is not None:
                        try:
                            await handler(headers, body)
                        except Exception:
                            # one poison message must not kill the read
                            # loop — the subscription would stay live at
                            # the broker while nothing reads it
                            LOGGER.exception(
                                "stomp message handler failed")
                elif command == "ERROR":
                    LOGGER.warning("stomp error frame: %s",
                                   headers.get("message"))
        except (StompProtocolError, asyncio.IncompleteReadError,
                ConnectionError):
            pass

    async def _send(self, data: bytes) -> None:
        if self._writer is None:
            raise StompProtocolError("not connected")
        self._writer.write(data)
        await self._writer.drain()

    async def send(self, destination: str, body: bytes,
                   headers: Optional[Dict[str, str]] = None) -> None:
        hdrs = {"destination": destination, **(headers or {})}
        await self._send(encode_frame("SEND", hdrs, body))

    async def subscribe(self, destination: str,
                        handler: Callable[[Dict[str, str], bytes],
                                          Awaitable[None]]) -> str:
        self._sub_seq += 1
        sub_id = f"sub-{self._sub_seq}"
        self._handlers[sub_id] = handler
        await self._send(encode_frame(
            "SUBSCRIBE", {"id": sub_id, "destination": destination,
                          "ack": "auto"}))
        return sub_id

    async def disconnect(self) -> None:
        if self._writer is not None:
            try:
                await self._send(encode_frame("DISCONNECT", {}))
            except (StompProtocolError, ConnectionError, OSError):
                pass
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None
        if self._read_task is not None:
            self._read_task.cancel()
            self._read_task = None
