"""Device-facing transports + wire protocol.

Reference: sitewhere-communication (SURVEY.md §2.2) — the device<->cloud
protobuf protocol (sitewhere.proto:6-133: SiteWhere.Command device->cloud,
Device.Command cloud->device, Model.* event messages), the MQTT lifecycle
base (mqtt/MqttLifecycleComponent.java), plus the receiver transports hosted
by service-event-sources (MQTT/CoAP/socket/WebSocket/HTTP).

TPU-first design: the wire format's hot event types (measurement, location,
alert) use a fixed-width little-endian binary layout so the host ingest tier
can decode frames straight into SoA columns (numpy now, the native C++
batch decoder for the same layout in native/) without per-event object
churn. Control messages (registration, commands) ride a msgpack profile.

No external broker processes: the MQTT broker and CoAP server here are
in-process asyncio implementations of the wire protocols themselves, so the
platform is self-contained the way the reference's embedded ActiveMQ broker
option is (sources/activemq/ActiveMQBroker).
"""

from sitewhere_tpu.transport.wire import (
    MessageType, WireCodec, WireError, decode_frames, encode_frame)
from sitewhere_tpu.transport.mqtt import MqttBroker, MqttClient
from sitewhere_tpu.transport.protobuf_compat import (
    ProtobufCompatDecoder, ProtobufSpecCommandEncoder)

__all__ = [
    "MessageType", "WireCodec", "WireError", "decode_frames", "encode_frame",
    "MqttBroker", "MqttClient",
    "ProtobufCompatDecoder", "ProtobufSpecCommandEncoder",
]
