"""In-process MQTT 3.1.1 broker + asyncio client.

Reference: the platform's device side speaks MQTT everywhere — inbound
events (service-event-sources mqtt/MqttInboundEventReceiver.java:39),
outbound commands (service-command-delivery
destination/mqtt/MqttCommandDeliveryProvider.java), connectors
(connector/mqtt/MqttOutboundConnector) — against an *external* broker
(HiveMQ/Mosquitto), with an embedded ActiveMQ broker option for self-
contained deployments. Here both ends are in-repo: a minimal, correct
MQTT 3.1.1 broker (CONNECT/PUBLISH QoS0+1/SUBSCRIBE with +/# wildcards/
retain/ping) and a client, so the whole platform runs without external
processes and tests drive real wire traffic (SURVEY.md §4).

Not implemented (not needed by the platform): QoS 2, persistent sessions,
wills. Unknown-flag packets are rejected by disconnect, per spec.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

# packet types
CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 8, 9, 10, 11
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14


def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        out.append(byte | 0x80 if n else byte)
        if not n:
            return bytes(out)


async def _read_varint(reader: asyncio.StreamReader) -> int:
    mult, value = 1, 0
    for _ in range(4):
        (byte,) = await reader.readexactly(1)
        value += (byte & 0x7F) * mult
        if not byte & 0x80:
            return value
        mult *= 128
    raise MqttProtocolError("malformed remaining length")


def _utf8(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _encode_varint(len(body)) + body


class MqttProtocolError(Exception):
    pass


def topic_matches(flt: str, topic: str) -> bool:
    """MQTT topic filter matching with + (one level) and # (remainder)."""
    fparts = flt.split("/")
    tparts = topic.split("/")
    for i, fp in enumerate(fparts):
        if fp == "#":
            return True
        if i >= len(tparts):
            return False
        if fp != "+" and fp != tparts[i]:
            return False
    return len(fparts) == len(tparts)


async def _read_packet(reader: asyncio.StreamReader) -> Tuple[int, int, bytes]:
    (first,) = await reader.readexactly(1)
    length = await _read_varint(reader)
    body = await reader.readexactly(length) if length else b""
    return first >> 4, first & 0x0F, body


@dataclass
class _Session:
    client_id: str
    writer: asyncio.StreamWriter
    subscriptions: Dict[str, int] = field(default_factory=dict)  # filter -> qos
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    async def send(self, data: bytes) -> None:
        async with self.lock:
            self.writer.write(data)
            await self.writer.drain()


class MqttBroker:
    """Asyncio MQTT broker. `port=0` binds an ephemeral port (see .port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._sessions: Dict[str, _Session] = {}
        self._retained: Dict[str, Tuple[bytes, int]] = {}  # topic -> (payload, qos)
        self._packet_id = 0
        # observability hook: (client_id, topic, payload) for every publish
        self.on_publish: Optional[Callable[[str, str, bytes], None]] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        for session in list(self._sessions.values()):
            session.writer.close()
        self._sessions.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- connection handling ----------------------------------------------
    async def _handle_client(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        session: Optional[_Session] = None
        try:
            ptype, _, body = await _read_packet(reader)
            if ptype != CONNECT:
                raise MqttProtocolError("first packet must be CONNECT")
            session = await self._on_connect(body, writer)
            while True:
                ptype, flags, body = await _read_packet(reader)
                if ptype == PUBLISH:
                    await self._on_publish(session, flags, body)
                elif ptype == SUBSCRIBE:
                    await self._on_subscribe(session, body)
                elif ptype == UNSUBSCRIBE:
                    await self._on_unsubscribe(session, body)
                elif ptype == PINGREQ:
                    await session.send(_packet(PINGRESP, 0, b""))
                elif ptype == PUBACK:
                    pass  # QoS1 outbound: fire-and-forget in-proc
                elif ptype == DISCONNECT:
                    break
                else:
                    raise MqttProtocolError(f"unsupported packet {ptype}")
        except (asyncio.IncompleteReadError, ConnectionResetError,
                MqttProtocolError):
            pass
        finally:
            if session is not None and \
                    self._sessions.get(session.client_id) is session:
                # only drop OUR registration — a reconnect with the same
                # client id may already have replaced it (session takeover)
                self._sessions.pop(session.client_id, None)
            writer.close()

    async def _on_connect(self, body: bytes,
                          writer: asyncio.StreamWriter) -> _Session:
        pos = 0
        (proto_len,) = struct.unpack_from("!H", body, pos)
        pos += 2 + proto_len  # b"MQTT"
        pos += 1  # level
        connect_flags = body[pos]
        pos += 1
        pos += 2  # keepalive
        (cid_len,) = struct.unpack_from("!H", body, pos)
        pos += 2
        client_id = body[pos:pos + cid_len].decode() or f"anon-{id(writer)}"
        # will/user/pass fields are parsed past but unused
        session = _Session(client_id=client_id, writer=writer)
        old = self._sessions.pop(client_id, None)
        if old is not None:
            old.writer.close()
        self._sessions[client_id] = session
        await session.send(_packet(CONNACK, 0, b"\x00\x00"))
        return session

    async def _on_publish(self, session: _Session, flags: int,
                          body: bytes) -> None:
        qos = (flags >> 1) & 0x03
        retain = flags & 0x01
        pos = 0
        (tlen,) = struct.unpack_from("!H", body, pos)
        pos += 2
        topic = body[pos:pos + tlen].decode()
        pos += tlen
        if qos > 0:
            (pid,) = struct.unpack_from("!H", body, pos)
            pos += 2
        payload = body[pos:]
        if retain:
            if payload:
                self._retained[topic] = (payload, qos)
            else:
                self._retained.pop(topic, None)
        if qos == 1:
            await session.send(_packet(PUBACK, 0, struct.pack("!H", pid)))
        if self.on_publish is not None:
            self.on_publish(session.client_id, topic, payload)
        await self._fanout(topic, payload)

    async def _fanout(self, topic: str, payload: bytes) -> None:
        for session in list(self._sessions.values()):
            for flt, sub_qos in session.subscriptions.items():
                if topic_matches(flt, topic):
                    await self._deliver(session, topic, payload, sub_qos)
                    break  # one delivery per client even with overlapping subs

    async def _deliver(self, session: _Session, topic: str, payload: bytes,
                       qos: int) -> None:
        if qos == 0:
            body = _utf8(topic) + payload
            pkt = _packet(PUBLISH, 0, body)
        else:
            self._packet_id = (self._packet_id % 0xFFFF) + 1
            body = _utf8(topic) + struct.pack("!H", self._packet_id) + payload
            pkt = _packet(PUBLISH, 0x02, body)
        try:
            await session.send(pkt)
        except (ConnectionResetError, RuntimeError):
            self._sessions.pop(session.client_id, None)

    async def _on_subscribe(self, session: _Session, body: bytes) -> None:
        (pid,) = struct.unpack_from("!H", body, 0)
        pos = 2
        codes = bytearray()
        new_filters: List[str] = []
        while pos < len(body):
            (flen,) = struct.unpack_from("!H", body, pos)
            pos += 2
            flt = body[pos:pos + flen].decode()
            pos += flen
            qos = min(body[pos], 1)  # QoS2 downgraded to 1
            pos += 1
            session.subscriptions[flt] = qos
            codes.append(qos)
            new_filters.append(flt)
        await session.send(_packet(SUBACK, 0,
                                   struct.pack("!H", pid) + bytes(codes)))
        # retained delivery on new subscription
        for flt in new_filters:
            for topic, (payload, qos) in list(self._retained.items()):
                if topic_matches(flt, topic):
                    await self._deliver(session, topic, payload,
                                        min(qos, session.subscriptions[flt]))

    async def _on_unsubscribe(self, session: _Session, body: bytes) -> None:
        (pid,) = struct.unpack_from("!H", body, 0)
        pos = 2
        while pos < len(body):
            (flen,) = struct.unpack_from("!H", body, pos)
            pos += 2
            session.subscriptions.pop(body[pos:pos + flen].decode(), None)
            pos += flen
        await session.send(_packet(UNSUBACK, 0, struct.pack("!H", pid)))


class MqttClient:
    """Asyncio MQTT 3.1.1 client (QoS 0/1, subscribe callbacks)."""

    def __init__(self, host: str, port: int, client_id: str = ""):
        self.host = host
        self.port = port
        self.client_id = client_id or f"swtpu-{id(self):x}"
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._read_task: Optional[asyncio.Task] = None
        self._packet_id = 0
        self._acks: Dict[int, asyncio.Future] = {}
        self._suback: Dict[int, asyncio.Future] = {}
        self._handlers: List[Tuple[str, Callable[[str, bytes],
                                                 Optional[Awaitable]]]] = []
        self._write_lock: Optional[asyncio.Lock] = None

    async def connect(self, timeout_s: float = 5.0) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._write_lock = asyncio.Lock()
        body = (_utf8("MQTT") + bytes([4]) + bytes([0x02])  # clean session
                + struct.pack("!H", 60) + _utf8(self.client_id))
        await self._send(_packet(CONNECT, 0, body))
        ptype, _, _ = await asyncio.wait_for(_read_packet(self._reader),
                                             timeout_s)
        if ptype != CONNACK:
            raise MqttProtocolError("expected CONNACK")
        self._read_task = asyncio.create_task(self._read_loop())

    async def _send(self, data: bytes) -> None:
        async with self._write_lock:
            self._writer.write(data)
            await self._writer.drain()

    def _next_pid(self) -> int:
        self._packet_id = (self._packet_id % 0xFFFF) + 1
        return self._packet_id

    async def publish(self, topic: str, payload: bytes, qos: int = 0,
                      retain: bool = False, timeout_s: float = 5.0) -> None:
        flags = (qos << 1) | (1 if retain else 0)
        if qos == 0:
            await self._send(_packet(PUBLISH, flags, _utf8(topic) + payload))
            return
        pid = self._next_pid()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._acks[pid] = fut
        body = _utf8(topic) + struct.pack("!H", pid) + payload
        await self._send(_packet(PUBLISH, flags, body))
        await asyncio.wait_for(fut, timeout_s)

    async def subscribe(self, topic_filter: str,
                        handler: Callable[[str, bytes], Optional[Awaitable]],
                        qos: int = 1, timeout_s: float = 5.0) -> None:
        pid = self._next_pid()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._suback[pid] = fut
        self._handlers.append((topic_filter, handler))
        body = (struct.pack("!H", pid) + _utf8(topic_filter) + bytes([qos]))
        await self._send(_packet(SUBSCRIBE, 0x02, body))
        await asyncio.wait_for(fut, timeout_s)

    async def ping(self) -> None:
        await self._send(_packet(PINGREQ, 0, b""))

    async def _read_loop(self) -> None:
        try:
            while True:
                ptype, flags, body = await _read_packet(self._reader)
                if ptype == PUBLISH:
                    await self._on_publish(flags, body)
                elif ptype == PUBACK:
                    (pid,) = struct.unpack_from("!H", body, 0)
                    fut = self._acks.pop(pid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(None)
                elif ptype == SUBACK:
                    (pid,) = struct.unpack_from("!H", body, 0)
                    fut = self._suback.pop(pid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(None)
                elif ptype in (PINGRESP, UNSUBACK):
                    pass
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.CancelledError):
            pass

    async def _on_publish(self, flags: int, body: bytes) -> None:
        qos = (flags >> 1) & 0x03
        (tlen,) = struct.unpack_from("!H", body, 0)
        pos = 2
        topic = body[pos:pos + tlen].decode()
        pos += tlen
        if qos > 0:
            (pid,) = struct.unpack_from("!H", body, pos)
            pos += 2
            await self._send(_packet(PUBACK, 0, struct.pack("!H", pid)))
        payload = body[pos:]
        for flt, handler in self._handlers:
            if topic_matches(flt, topic):
                result = handler(topic, payload)
                if asyncio.iscoroutine(result):
                    await result
                break

    async def disconnect(self) -> None:
        if self._writer is None:
            return
        try:
            await self._send(_packet(DISCONNECT, 0, b""))
        except (ConnectionResetError, RuntimeError):
            pass
        if self._read_task is not None:
            self._read_task.cancel()
        self._writer.close()
        self._writer = None
