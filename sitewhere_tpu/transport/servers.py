"""Stream/HTTP/WebSocket listener servers for device ingest.

Reference: service-event-sources socket/SocketInboundEventReceiver.java
(raw TCP), WebSocketEventReceiver, and the polling/HTTP receivers. Each
server here accepts device payloads and hands complete binary messages to
an async callback; framing for the TCP path is the wire-protocol frame
header (transport/wire.py), so a connection can stream many events.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional

from aiohttp import web

from sitewhere_tpu.transport.wire import WireError, decode_frames

PayloadHandler = Callable[[bytes], Awaitable[None]]


class SocketEventServer:
    """TCP listener; splits the byte stream into wire frames and forwards
    each complete frame (header included) to the handler."""

    def __init__(self, handler: PayloadHandler, host: str = "127.0.0.1",
                 port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._client, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        buffer = b""
        try:
            while True:
                chunk = await reader.read(65536)
                if not chunk:
                    break
                buffer += chunk
                try:
                    frames, rest = decode_frames(buffer)
                except WireError:
                    break  # corrupt stream (or frame over cap): drop it
                if frames:
                    # forward the consumed prefix verbatim — no re-encode;
                    # the source's WireDecoder handles multi-frame payloads
                    await self.handler(buffer[:len(buffer) - len(rest)])
                buffer = rest
        finally:
            writer.close()


class WebSocketEventServer:
    """WebSocket listener: each binary message is one complete payload."""

    def __init__(self, handler: PayloadHandler, host: str = "127.0.0.1",
                 port: int = 0, path: str = "/events"):
        self.handler = handler
        self.host = host
        self.port = port
        self.path = path
        self._server = None

    async def start(self) -> None:
        import websockets

        async def on_connection(websocket) -> None:
            async for message in websocket:
                if isinstance(message, str):
                    message = message.encode()
                await self.handler(message)

        self._server = await websockets.serve(on_connection, self.host,
                                              self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


class HttpEventServer:
    """HTTP POST listener (`POST /events`): request body is one payload.
    Covers both the reference's HTTP receiver and its polling REST receiver's
    server half."""

    def __init__(self, handler: PayloadHandler, host: str = "127.0.0.1",
                 port: int = 0, path: str = "/events"):
        self.handler = handler
        self.host = host
        self.port = port
        self.path = path
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> None:
        app = web.Application()

        async def post(request: web.Request) -> web.Response:
            await self.handler(await request.read())
            return web.json_response({"accepted": True})

        app.router.add_post(self.path, post)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
