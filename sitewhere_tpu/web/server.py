"""Threaded HTTP server hosting the REST gateway.

Reference: service-web-rest is a Spring Boot web app fronting every backend
service via gRPC ApiDemux channels (SURVEY.md §3.5); auth is a JWT filter
(security/jwt/TokenAuthenticationFilter.java) with tokens minted by
`auth/controllers/JwtService.java` from HTTP Basic credentials. Here the
gateway calls tenant engines in-process; the HTTP layer is the stdlib
ThreadingHTTPServer so the framework stays dependency-free.

Auth model (mirrors the reference):
  POST/GET /authapi/jwt         HTTP Basic → {"token": <jwt>}
  everything under /api/**      Authorization: Bearer <jwt>
  tenant routing                X-SiteWhere-Tenant header (tenant token;
                                the reference's X-SiteWhere-Tenant-Id)
"""

from __future__ import annotations

import base64
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import urlparse

from sitewhere_tpu.errors import AuthError, SiteWhereError
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.web.marshal import to_jsonable
from sitewhere_tpu.web.router import Request, Router

LOGGER = logging.getLogger("sitewhere.web")


class SseStream:
    """Handler return type for server-sent events: the server streams each
    item from `events()` as an SSE `data:` frame (JSON-encoded unless str)
    until the generator ends or the client disconnects. The reference pushes
    the same live feeds over a WebSocket (service-web-rest
    ws/components/TopologyBroadcaster.java); SSE keeps it dependency-free."""

    def __init__(self, events):
        self.events = events  # iterable / generator


class RestServer(LifecycleComponent):
    """HTTP front door for a SiteWhereInstance."""

    def __init__(self, instance, host: str = "127.0.0.1", port: int = 0,
                 token_expiration_minutes: int = 60):
        super().__init__("rest-server")
        self.instance = instance
        self.router = Router()
        self.host = host
        self.port = port
        self.token_expiration_minutes = token_expiration_minutes
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        from sitewhere_tpu.web.controllers import register_all
        register_all(self.router, instance, self)
        from sitewhere_tpu.web.admin import register_admin
        register_admin(self.router)
        from sitewhere_tpu.web.explorer import register_explorer
        register_explorer(self.router)

    # -- lifecycle ---------------------------------------------------------
    def on_start(self, monitor) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route to framework logging
                LOGGER.debug("%s %s", self.address_string(), fmt % args)

            def _handle(self):
                server._handle_http(self)

            do_GET = do_POST = do_PUT = do_DELETE = _handle

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="rest-server", daemon=True)
        self._thread.start()
        LOGGER.info("REST gateway listening on %s:%d", self.host, self.port)

    def on_stop(self, monitor) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- request handling --------------------------------------------------
    def _authenticate_basic(self, header: str) -> str:
        """HTTP Basic credentials → JWT (the /authapi/jwt flow)."""
        try:
            decoded = base64.b64decode(header.split(" ", 1)[1]).decode("utf-8")
            username, password = decoded.split(":", 1)
        except Exception:
            raise AuthError("malformed basic credentials")
        user = self.instance.user_management.authenticate(username, password)
        return self.instance.token_management.generate_token(
            user.username,
            authorities=self.instance.user_management.get_user_authorities(
                user.username),
            expiration_minutes=self.token_expiration_minutes)

    def _claims_for(self, handler: BaseHTTPRequestHandler) -> Optional[dict]:
        header = handler.headers.get("Authorization", "")
        if header.startswith("Bearer "):
            from sitewhere_tpu.security.tokens import InvalidTokenError
            try:
                return self.instance.token_management.get_claims(
                    header.split(" ", 1)[1])
            except InvalidTokenError as err:
                raise AuthError(str(err))
        return None

    def _handle_http(self, handler: BaseHTTPRequestHandler) -> None:
        try:
            parsed = urlparse(handler.path)
            body: Any = None
            raw_body: Optional[bytes] = None
            length = int(handler.headers.get("Content-Length") or 0)
            if length:
                raw_body = handler.rfile.read(length)
                ctype = handler.headers.get("Content-Type", "")
                if "json" in ctype or not ctype:
                    body = json.loads(raw_body) if raw_body.strip() else None
                else:
                    body = raw_body

            # token minting endpoint (basic auth, no bearer required)
            if parsed.path.rstrip("/") == "/authapi/jwt":
                auth_header = handler.headers.get("Authorization", "")
                if not auth_header.startswith("Basic "):
                    raise AuthError("basic authentication required")
                token = self._authenticate_basic(auth_header)
                self._respond(handler, 200, {"token": token})
                return

            request = Request(
                method=handler.command, path=parsed.path,
                query=self.router.parse_query(parsed.query), body=body,
                raw_body=raw_body,
                headers={k: v for k, v in handler.headers.items()},
                claims=self._claims_for(handler),
                tenant=handler.headers.get(
                    "X-SiteWhere-Tenant",
                    handler.headers.get("X-SiteWhere-Tenant-Id")))
            # W3C trace-context ingress: an incoming `traceparent` header
            # parents the dispatch span (and, via the tracer's
            # thread-local stack, every span the handler opens on this
            # thread); the response echoes the server span's context so
            # callers can stitch their traces to ours.
            from sitewhere_tpu.runtime.faults import fault_point
            from sitewhere_tpu.runtime.tracing import (
                GLOBAL_TRACER, extract_traceparent, inject_traceparent)
            # drill: a stalled REST worker (delay-mode rule) holds this
            # thread mid-request — ThreadingHTTPServer keeps serving on
            # the others, which is exactly what the drill verifies
            fault_point("rest_worker_stall")
            parent_ctx = extract_traceparent(
                handler.headers.get("traceparent"))
            with GLOBAL_TRACER.span(
                    f"rest.{handler.command.lower()}",
                    parent=parent_ctx, path=parsed.path) as span:
                handler._sw_traceparent = inject_traceparent(span)
                result = self.router.dispatch(request)
            if isinstance(result, SseStream):
                self._stream_sse(handler, result)
                return
            status, ctype = 200, None
            if isinstance(result, tuple):
                if len(result) == 3:
                    status, result, ctype = result
                else:
                    status, result = result
            self._respond(handler, status, result, ctype)
        except SiteWhereError as err:
            self._respond(handler, err.http_status,
                          {"message": str(err), "errorCode": int(err.code)})
        except json.JSONDecodeError as err:
            self._respond(handler, 400, {"message": f"invalid JSON: {err}"})
        except Exception as err:  # controller bug — surface as 500
            LOGGER.exception("unhandled REST error")
            self._respond(handler, 500, {"message": str(err)})

    def _stream_sse(self, handler: BaseHTTPRequestHandler,
                    stream: SseStream) -> None:
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Connection", "close")
        handler.end_headers()
        try:
            for event in stream.events:
                if isinstance(event, str) and event.startswith(":"):
                    frame = f"{event}\n\n"     # SSE comment (keepalive)
                elif isinstance(event, str):
                    frame = f"data: {event}\n\n"
                else:
                    frame = f"data: {json.dumps(to_jsonable(event))}\n\n"
                handler.wfile.write(frame.encode())
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away — the generator's finally cleans up
        except Exception:
            # the 200 header block is committed: a second send_response
            # would corrupt the stream, so terminate it instead
            LOGGER.exception("SSE stream generator failed")
        finally:
            close = getattr(stream.events, "close", None)
            if close is not None:
                close()

    def _respond(self, handler: BaseHTTPRequestHandler, status: int,
                 payload: Any, ctype: Optional[str] = None) -> None:
        if isinstance(payload, bytes):
            data = payload
            ctype = ctype or "application/octet-stream"
        else:
            data = json.dumps(to_jsonable(payload)).encode("utf-8")
            ctype = "application/json"
        try:
            handler.send_response(status)
            handler.send_header("Content-Type", ctype)
            handler.send_header("Content-Length", str(len(data)))
            traceparent = getattr(handler, "_sw_traceparent", None)
            if traceparent:
                handler.send_header("traceparent", traceparent)
            handler.end_headers()
            handler.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass
