"""OpenAPI 3.0 document generated from the live route table.

Reference: service-web-rest ships Swagger (RestMvcConfiguration swagger bean,
the admin UI's API explorer). Here the router IS the source of truth: every
registered route contributes a path item with its method, path/query
parameters, auth requirement, and a tag derived from the collection segment,
so the document can never drift from the actual surface.
"""

from __future__ import annotations

from typing import Any, Dict

from sitewhere_tpu.web.router import Router


def _tag_of(segments) -> str:
    # /api/<collection>/... -> collection; /authapi/... -> auth
    if segments and segments[0] == "api" and len(segments) > 1:
        return segments[1]
    return segments[0] if segments else "root"


def generate_openapi(router: Router, title: str = "sitewhere-tpu REST API",
                     version: str = "1.0") -> Dict[str, Any]:
    paths: Dict[str, Dict[str, Any]] = {}
    tags = set()
    for route in router._routes:
        path = "/" + "/".join(route.segments)
        tag = _tag_of(route.segments)
        tags.add(tag)
        params = [{
            "name": seg[1:-1], "in": "path", "required": True,
            "schema": {"type": "string"},
        } for seg in route.segments if seg.startswith("{")]
        # derived from the full path so re-registered handlers (e.g. script
        # routes under both /api and /api/tenants/{token}) stay unique
        op_id = route.method.lower() + "_" + "_".join(
            seg.strip("{}") for seg in route.segments)
        op: Dict[str, Any] = {
            "tags": [tag],
            "operationId": op_id,
            "parameters": params,
            "responses": {"200": {"description": "success"},
                          "400": {"description": "invalid request"},
                          "404": {"description": "not found"}},
        }
        if route.auth:
            op["security"] = [{"bearerAuth": []}]
            op["responses"]["401"] = {"description": "unauthenticated"}
            if route.authority:
                op["x-required-authority"] = str(route.authority)
                op["responses"]["403"] = {"description": "forbidden"}
        if route.method in ("POST", "PUT"):
            op["requestBody"] = {"content": {"application/json": {
                "schema": {"type": "object"}}}}
        paths.setdefault(path, {})[route.method.lower()] = op
    return {
        "openapi": "3.0.3",
        "info": {"title": title, "version": version},
        "tags": [{"name": t} for t in sorted(tags)],
        "components": {"securitySchemes": {"bearerAuth": {
            "type": "http", "scheme": "bearer", "bearerFormat": "JWT"}}},
        "paths": dict(sorted(paths.items())),
    }
