"""REST gateway (reference: service-web-rest — 27 Spring MVC controllers,
JWT auth filter, Swagger). Here: a dependency-free HTTP tier on the stdlib
threading HTTP server, JSON marshaling of the model dataclasses, JWT bearer
auth, and controllers registered against a tiny router."""

from sitewhere_tpu.web.router import Request, Router
from sitewhere_tpu.web.server import RestServer

__all__ = ["Request", "Router", "RestServer"]
