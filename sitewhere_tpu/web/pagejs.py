"""Shared JS fragments for the self-contained operator pages (/admin,
/api/explorer). Both pages inline their scripts — no CDN, the deployment
may have zero egress — so shared behavior lives here once: the
HTML-escape helper (operator data interpolated into markup must never
execute with the page's JWT in scope) and the Basic-auth -> JWT mint
against /authapi/jwt.
"""

ESC_JS = r"""
const esc=s=>String(s).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
"""

MINT_JWT_JS = r"""
async function mintJwt(u,p){
  const r=await fetch('/authapi/jwt',{method:'POST',
    headers:{'Authorization':'Basic '+btoa(u+':'+p)}});
  if(!r.ok)throw new Error('auth failed ('+r.status+')');
  return (await r.json()).token;}
"""
