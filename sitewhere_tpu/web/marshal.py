"""JSON marshaling between model dataclasses and the REST wire format.

Reference: service-web-rest marshals via Jackson + `*MarshalHelper` classes
(sitewhere-core `device/marshaling/`). Here dataclasses serialize through a
single recursive converter (enums by value, bytes as base64) and entity
creation goes through the same coercion layer the persistence tier uses
(registry/store.py `_entity_from_json`) so REST payloads and stored payloads
stay one format.
"""

from __future__ import annotations

import base64
import dataclasses
import json
from typing import Any, Dict, Type, TypeVar

T = TypeVar("T")


def to_jsonable(obj: Any) -> Any:
    """Model object → plain JSON-serializable structure."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: to_jsonable(v)
                for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {k: to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode("ascii")
    if hasattr(obj, "value") and not isinstance(obj, (str, int, float, bool)):
        return obj.value  # enums
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    # enums subclass int/str above; anything else stringifies
    return str(obj)


def results_to_jsonable(results) -> Dict[str, Any]:
    """SearchResults → {numResults, results} (reference paging envelope)."""
    return {"numResults": results.num_results,
            "results": [to_jsonable(r) for r in results.results]}


def entity_from_payload(cls: Type[T], payload: Dict[str, Any]) -> T:
    """JSON body → model dataclass, with enum/nested coercion."""
    from sitewhere_tpu.registry.store import _entity_from_json
    return _entity_from_json(cls, json.dumps(payload))
