"""API explorer: a browsable, executable view of the REST surface.

Reference: service-web-rest ships Swagger UI over its springfox OpenAPI
document; here the same role is a single self-contained page (vanilla
JS, no CDN — the deployment may have zero egress) served at
``/api/explorer`` that renders the live ``/api/openapi.json`` route
table grouped by tag, expands each operation's parameters and body
schema, and offers try-it-out with a JWT minted via ``/authapi/jwt``.
"""

from __future__ import annotations

from sitewhere_tpu.web.pagejs import ESC_JS, MINT_JWT_JS

_PAGE = r"""<!doctype html><html><head><meta charset="utf-8">
<title>sitewhere-tpu API explorer</title><style>
 body{font:14px/1.45 system-ui,sans-serif;margin:0;background:#f6f7f9;
      color:#1c2733}
 header{background:#16324f;color:#fff;padding:10px 18px;display:flex;
        gap:14px;align-items:center}
 header h1{font-size:15px;margin:0;font-weight:600}
 header input{border:0;border-radius:4px;padding:5px 8px;font-size:13px}
 header button{border:0;border-radius:4px;padding:5px 12px;cursor:pointer}
 #authstate{font-size:12px;opacity:.85}
 main{max-width:1060px;margin:14px auto;padding:0 14px}
 .tag{background:#fff;border:1px solid #dfe3e8;border-radius:8px;
      margin-bottom:12px;overflow:hidden}
 .tag>h2{font-size:13px;margin:0;padding:9px 14px;cursor:pointer;
      text-transform:uppercase;letter-spacing:.04em;color:#4a5a6a}
 .op{border-top:1px solid #eef1f4;padding:8px 14px}
 .op>.line{cursor:pointer;display:flex;gap:10px;align-items:baseline}
 .m{font-weight:700;font-size:12px;width:58px;text-align:center;
    border-radius:4px;padding:2px 0;color:#fff}
 .m.get{background:#2e7d32}.m.post{background:#1565c0}
 .m.put{background:#ef6c00}.m.delete{background:#c62828}
 .path{font-family:ui-monospace,monospace;font-size:13px}
 .sum{color:#6b7a89;font-size:12px}
 .detail{display:none;margin:8px 0 4px 68px;font-size:13px}
 .detail textarea{width:95%;font-family:ui-monospace,monospace;
    font-size:12px;min-height:60px}
 .detail input{font-family:ui-monospace,monospace;font-size:12px;
    margin:2px 4px 2px 0}
 .detail pre{background:#0f1c28;color:#d7e3ee;padding:8px;
    border-radius:6px;overflow:auto;max-height:340px;font-size:12px}
 .detail button{border:0;border-radius:4px;background:#16324f;color:#fff;
    padding:5px 14px;cursor:pointer;margin:6px 0}
 .auth{font-size:11px;color:#8a62121f;background:#fff3df;color:#8a6212;
    border-radius:4px;padding:1px 6px}
 #filter{margin:0 0 12px;width:100%;padding:7px 10px;border:1px solid
    #dfe3e8;border-radius:6px;font-size:13px}
</style></head><body>
<header><h1>sitewhere-tpu API</h1>
 <input id="u" placeholder="username" value="admin">
 <input id="p" type="password" placeholder="password" value="password">
 <button onclick="signin()">Sign in</button>
 <span id="authstate">anonymous</span>
</header>
<main>
 <input id="filter" placeholder="filter paths…" oninput="render()">
 <div id="tags"></div>
</main>
<script>
let TOKEN=null,DOC=null;
__SHARED_JS__
async function signin(){
  const u=document.getElementById('u').value,
        p=document.getElementById('p').value;
  try{
    TOKEN=await mintJwt(u,p);
    document.getElementById('authstate').textContent='signed in as '+u;
  }catch(e){document.getElementById('authstate').textContent=e.message}}
function opId(m,p){return (m+p).replace(/[^a-z0-9]/gi,'_')}
function render(){
  if(!DOC)return;  // openapi doc not loaded yet (filter typed early)
  const q=document.getElementById('filter').value.toLowerCase();
  const groups={};
  for(const [path,ops] of Object.entries(DOC.paths||{})){
    if(q&&!path.toLowerCase().includes(q))continue;
    for(const [method,op] of Object.entries(ops)){
      const tag=(op.tags&&op.tags[0])||path.split('/')[2]||'misc';
      (groups[tag]=groups[tag]||[]).push([method,path,op]);}}
  document.getElementById('tags').innerHTML=
    Object.keys(groups).sort().map(tag=>`<div class="tag">
     <h2>${esc(tag)} (${groups[tag].length})</h2>
     ${groups[tag].sort((a,b)=>a[1]<b[1]?-1:1).map(([m,p,op])=>{
       const id=opId(m,p);
       const params=(op.parameters||[]).filter(x=>x.in==='path');
       return `<div class="op">
        <div class="line" onclick="toggle('${id}')">
         <span class="m ${m}">${m.toUpperCase()}</span>
         <span class="path">${esc(p)}</span>
         <span class="sum">${esc(op.summary||'')}</span>
         ${op.security&&op.security.length?
           '<span class="auth">JWT</span>':''}
        </div>
        <div class="detail" id="${id}">
         ${params.map(x=>`<label>${esc(x.name)}
           <input data-param="${esc(x.name)}" placeholder="${esc(x.name)}">
           </label>`).join('')}
         ${['post','put'].includes(m)?
           '<div><textarea data-body placeholder="JSON body"></textarea></div>':''}
         <button onclick="call('${m}','${esc(p)}','${id}')">Send</button>
         <pre data-out>—</pre>
        </div></div>`}).join('')}
    </div>`).join('')||'<p>(no matching paths)</p>';}
function toggle(id){
  const el=document.getElementById(id);
  el.style.display=el.style.display==='block'?'none':'block';}
async function call(method,path,id){
  const el=document.getElementById(id);
  for(const inp of el.querySelectorAll('input[data-param]'))
    path=path.replace('{'+inp.dataset.param+'}',
                      ()=>encodeURIComponent(inp.value));
  const opt={method:method.toUpperCase(),headers:{}};
  if(TOKEN)opt.headers['Authorization']='Bearer '+TOKEN;
  const body=el.querySelector('textarea[data-body]');
  if(body&&body.value.trim()){
    opt.headers['Content-Type']='application/json';opt.body=body.value;}
  const out=el.querySelector('pre[data-out]');
  try{
    const r=await fetch(path,opt);
    const text=await r.text();
    let shown=text;
    try{shown=JSON.stringify(JSON.parse(text),null,2)}catch(e){}
    out.textContent=r.status+' '+r.statusText+'\n\n'+
      shown.slice(0,20000);
  }catch(e){out.textContent=String(e)}}
fetch('/api/openapi.json').then(r=>r.json()).then(doc=>{
  DOC=doc;render();}).catch(e=>{
  document.getElementById('tags').textContent=
    'failed to load /api/openapi.json: '+e;});
</script></body></html>
"""


def register_explorer(router) -> None:
    """Serve the explorer at /api/explorer (the page itself is public,
    like the OpenAPI document it renders; every call it makes carries the
    JWT minted on sign-in)."""

    page = _PAGE.replace("__SHARED_JS__", ESC_JS + MINT_JWT_JS)

    def explorer_page(request):
        return 200, page.encode("utf-8"), "text/html; charset=utf-8"

    router.get("/api/explorer", explorer_page, auth=False)
