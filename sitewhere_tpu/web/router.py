"""Minimal HTTP routing core for the REST gateway.

Reference: service-web-rest uses Spring MVC annotations
(`rest/controllers/*.java`, e.g. Assignments.java:98-160) + a JWT filter
(security/jwt/TokenAuthenticationFilter.java). This replaces that stack with
an explicit route table: `{token}`-style path templates, per-route authority
requirements, and a Request object carrying parsed query/body/claims.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from sitewhere_tpu.errors import AuthError, SiteWhereError
from sitewhere_tpu.model.common import DateRangeCriteria, SearchCriteria


@dataclass
class Request:
    """One parsed HTTP request, handed to controller functions."""

    method: str = "GET"
    path: str = "/"
    params: Dict[str, str] = field(default_factory=dict)
    query: Dict[str, List[str]] = field(default_factory=dict)
    body: Any = None
    raw_body: Optional[bytes] = None       # undecoded bytes (binary uploads)
    headers: Dict[str, str] = field(default_factory=dict)
    claims: Optional[Dict] = None          # JWT claims once authenticated
    tenant: Optional[str] = None           # resolved tenant token
    context: Any = None                    # per-request controller context

    @property
    def username(self) -> str:
        return (self.claims or {}).get("sub", "")

    @property
    def authorities(self) -> List[str]:
        return (self.claims or {}).get("auth", [])

    def query_one(self, name: str, default: Optional[str] = None
                  ) -> Optional[str]:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def query_int(self, name: str, default: int) -> int:
        val = self.query_one(name)
        return int(val) if val is not None else default

    def query_bool(self, name: str, default: bool = False) -> bool:
        val = self.query_one(name)
        if val is None:
            return default
        return val.lower() in ("1", "true", "yes")

    def criteria(self) -> SearchCriteria:
        """Paging params (reference: RestControllerBase paging args)."""
        return SearchCriteria(page_number=self.query_int("page", 1),
                              page_size=self.query_int("pageSize", 100))

    def date_criteria(self) -> DateRangeCriteria:
        crit = DateRangeCriteria(page_number=self.query_int("page", 1),
                                 page_size=self.query_int("pageSize", 100))
        start = self.query_one("startDate")
        end = self.query_one("endDate")
        if start is not None:
            crit.start_date = int(start)
        if end is not None:
            crit.end_date = int(end)
        return crit


@dataclass
class _Route:
    method: str
    segments: Tuple[str, ...]
    handler: Callable[[Request], Any]
    auth: bool
    authority: Optional[str]


class Router:
    """Explicit route table with `{param}` path templates."""

    def __init__(self) -> None:
        self._routes: List[_Route] = []

    def add(self, method: str, pattern: str,
            handler: Callable[[Request], Any], auth: bool = True,
            authority: Optional[str] = None) -> None:
        segments = tuple(s for s in pattern.strip("/").split("/") if s)
        self._routes.append(_Route(method.upper(), segments, handler, auth,
                                   authority))

    # convenience registrars
    def get(self, pattern, handler, **kw):
        self.add("GET", pattern, handler, **kw)

    def post(self, pattern, handler, **kw):
        self.add("POST", pattern, handler, **kw)

    def put(self, pattern, handler, **kw):
        self.add("PUT", pattern, handler, **kw)

    def delete(self, pattern, handler, **kw):
        self.add("DELETE", pattern, handler, **kw)

    @staticmethod
    def _match(route: _Route, parts: Tuple[str, ...]
               ) -> Optional[Dict[str, str]]:
        if len(route.segments) != len(parts):
            return None
        params: Dict[str, str] = {}
        for seg, part in zip(route.segments, parts):
            if seg.startswith("{") and seg.endswith("}"):
                params[seg[1:-1]] = part
            elif seg != part:
                return None
        return params

    def resolve(self, method: str, path: str
                ) -> Tuple[_Route, Dict[str, str]]:
        parts = tuple(s for s in path.strip("/").split("/") if s)
        found_path = False
        for route in self._routes:
            params = self._match(route, parts)
            if params is None:
                continue
            found_path = True
            if route.method == method.upper():
                return route, params
        if found_path:
            raise SiteWhereError("method not allowed", http_status=405)
        raise SiteWhereError(f"no route for {path}", http_status=404)

    def dispatch(self, request: Request) -> Any:
        route, params = self.resolve(request.method, request.path)
        request.params = params
        if route.auth:
            if request.claims is None:
                raise AuthError("authentication required")
            if route.authority and route.authority not in request.authorities:
                raise SiteWhereError(
                    f"missing authority {route.authority}", http_status=403)
        return route.handler(request)

    def parse_query(self, raw_query: str) -> Dict[str, List[str]]:
        return parse_qs(raw_query, keep_blank_values=True)
