"""Minimal admin console: one static page over the existing REST surface.

The reference ships a separate admin UI application (sitewhere-admin-ui)
driving the REST APIs; this is the in-repo equivalent — a dependency-free
single page (vanilla JS, no build step) served at ``/admin`` that signs in
via ``/authapi/jwt`` and drives topology, metrics, tenants (engine
start/stop/restart), logs, and checkpoints through the same endpoints any
operator script would use.
"""

from __future__ import annotations

_PAGE = """<!doctype html>
<html lang="en"><head><meta charset="utf-8">
<title>sitewhere-tpu admin</title>
<style>
 body{font:14px/1.45 system-ui,sans-serif;margin:0;background:#f4f5f7;color:#1b1f24}
 header{background:#1b2a41;color:#fff;padding:10px 20px;display:flex;
        align-items:center;gap:16px}
 header h1{font-size:16px;margin:0;font-weight:600}
 header .st{margin-left:auto;font-size:12px;opacity:.85}
 main{max-width:1100px;margin:18px auto;padding:0 16px;display:grid;
      grid-template-columns:1fr 1fr;gap:16px}
 section{background:#fff;border:1px solid #dfe3e8;border-radius:8px;
         padding:14px 16px}
 section h2{font-size:13px;margin:0 0 10px;text-transform:uppercase;
            letter-spacing:.05em;color:#57606a}
 table{width:100%;border-collapse:collapse;font-size:13px}
 td,th{text-align:left;padding:4px 6px;border-bottom:1px solid #eef0f3}
 th{color:#57606a;font-weight:600}
 .wide{grid-column:1/-1}
 .ok{color:#116329}.bad{color:#a40e26}
 button{font:12px system-ui;border:1px solid #c9d1d9;background:#f6f8fa;
        border-radius:6px;padding:3px 10px;cursor:pointer;margin-right:4px}
 button:hover{background:#eef1f4}
 #login{max-width:320px;margin:80px auto;background:#fff;padding:24px;
        border:1px solid #dfe3e8;border-radius:8px}
 #login input{width:100%;box-sizing:border-box;margin:6px 0 12px;
              padding:7px;border:1px solid #c9d1d9;border-radius:6px}
 pre{font-size:12px;max-height:260px;overflow:auto;background:#0d1117;
     color:#c9d1d9;padding:10px;border-radius:6px;margin:0}
 .kv{display:grid;grid-template-columns:auto 1fr;gap:2px 14px;font-size:13px}
 .kv div:nth-child(odd){color:#57606a}
</style></head><body>
<div id="login">
  <h1>sitewhere-tpu</h1>
  <input id="u" placeholder="username" value="admin">
  <input id="p" type="password" placeholder="password">
  <button onclick="signin()" style="width:100%;padding:8px">Sign in</button>
  <div id="lerr" class="bad"></div>
</div>
<div id="app" style="display:none">
<header><h1>sitewhere-tpu admin</h1><span id="inst"></span>
  <span class="st" id="stamp"></span></header>
<main>
 <section><h2>Topology</h2><div class="kv" id="topo"></div></section>
 <section><h2>Key metrics</h2><div class="kv" id="met"></div></section>
 <section class="wide"><h2>Tenant engines</h2>
   <table id="tenants"><thead><tr><th>tenant</th><th>engine</th>
   <th>actions</th></tr></thead><tbody></tbody></table></section>
 <section class="wide" id="clustersec" style="display:none">
   <h2>Cluster processes</h2>
   <table id="procs"><thead><tr><th>process</th><th>status</th>
   <th>tick</th><th>age</th><th>liveness</th></tr></thead>
   <tbody></tbody></table></section>
 <section class="wide"><h2>Pipeline rules</h2>
   <table id="rules"><thead><tr><th>token</th><th>type</th>
   <th>definition</th><th>active</th><th>actions</th></tr></thead>
   <tbody></tbody></table></section>
 <section><h2>Checkpoints</h2>
   <button onclick="ckpt()">Checkpoint now</button>
   <ul id="ckpts" style="font-size:13px"></ul></section>
 <section><h2>Recent logs</h2><pre id="logs"></pre></section>
</main></div>
<script>
let TOKEN=null;
__SHARED_JS__
const api=(p,opt={})=>fetch(p,{...opt,headers:{
  'Authorization':'Bearer '+TOKEN,'Content-Type':'application/json',
  ...(opt.headers||{})}}).then(r=>{
    if(!r.ok)throw new Error(p+' -> '+r.status);return r.json()});
async function signin(){
  const u=document.getElementById('u').value,p=document.getElementById('p').value;
  try{
    TOKEN=await mintJwt(u,p);
    document.getElementById('login').style.display='none';
    document.getElementById('app').style.display='';
    tick();setInterval(tick,2000);
  }catch(e){document.getElementById('lerr').textContent=e.message}}
function kv(el,obj){el.innerHTML=Object.entries(obj).map(
  ([k,v])=>`<div>${esc(k)}</div><div>${esc(v)}</div>`).join('')}
async function tick(){
  try{
    const t=await api('/api/instance/topology');
    document.getElementById('inst').textContent=t.instance_id;
    kv(document.getElementById('topo'),{status:t.status,
      pipeline:t.pipeline_enabled?'enabled':'disabled',
      engines:Object.keys(t.tenant_engines).length,
      failed:Object.keys(t.failed_tenant_engines).length||'none'});
    const body=document.querySelector('#tenants tbody');
    body.innerHTML=Object.entries(t.tenant_engines).map(([tok,st])=>
      `<tr><td>${esc(tok)}</td>
       <td class="${st==='STARTED'?'ok':'bad'}">${esc(st)}</td>
       <td>${['restart','stop','start'].map(op=>
         `<button data-tok="${esc(tok)}" data-op="${op}">${op}</button>`
        ).join('')}</td></tr>`).join('');
    if(t.processes){  // multi-host deployment: per-process heartbeats
      document.getElementById('clustersec').style.display='';
      document.querySelector('#procs tbody').innerHTML=
        Object.entries(t.processes).sort().map(([pid,p])=>
          `<tr><td>${esc(pid)}${pid==String(t.process_id)?' (this)':''}</td>
           <td>${esc(p.status??'?')}</td><td>${esc(p.tick??'')}</td>
           <td>${esc(p.age_s??'')}s</td>
           <td class="${p.stale?'bad':'ok'}">${p.stale?'STALE':'live'}</td>
           </tr>`).join('');
    }
    const m=await api('/api/instance/metrics');
    const pick={};
    for(const cat of Object.values(m)){           // {counters:{...},...}
      for(const [k,v] of Object.entries(cat||{})){
        if(/events|processed|alerts|dropped|drain|step/.test(k)){
          pick[k]=typeof v==='object'?(v.count??JSON.stringify(v)):v;}
        if(Object.keys(pick).length>=10)break;}}
    if(!Object.keys(pick).length)pick['(no activity yet)']='';
    kv(document.getElementById('met'),pick);
    const lg=await api('/api/instance/logs?limit=12');
    document.getElementById('logs').textContent=
      lg.records.map(r=>`${r.level??''} ${r.message??JSON.stringify(r)}`)
        .join('\\n')||'(no records)';
    try{const r=await api('/api/rules');
      const rows=[...(r.threshold||[]).map(x=>[x,'threshold',
          `${esc(x.measurement_name||'any')} ${esc(x.operator)} ${esc(x.threshold)}`]),
        ...(r.geofence||[]).map(x=>[x,'geofence',
          `${esc(x.condition)} zone ${esc(x.zone_token)}`])];
      document.querySelector('#rules tbody').innerHTML=rows.map(
        ([x,kind,def])=>`<tr><td>${esc(x.token)}</td><td>${kind}</td>
         <td>${def} → ${esc(x.alert_type)}</td>
         <td class="${x.active?'ok':'bad'}">${x.active?'yes':'no'}</td>
         <td><button data-rule="${esc(x.token)}">delete</button></td></tr>`
        ).join('')||'<tr><td colspan="5">(none)</td></tr>';}catch(e){}
    try{const c=await api('/api/instance/checkpoints');
      document.getElementById('ckpts').innerHTML=
        (c.checkpoints||[]).map(x=>`<li>${esc(x)}</li>`).join('')||
        '<li>(none)</li>';}catch(e){}
    document.getElementById('stamp').textContent=
      new Date().toLocaleTimeString();
  }catch(e){document.getElementById('stamp').textContent=e.message}}
document.addEventListener('click',ev=>{
  const b=ev.target.closest('button[data-tok]');
  if(b)eng(b.dataset.tok,b.dataset.op);
  const r=ev.target.closest('button[data-rule]');
  if(r)delRule(r.dataset.rule);});
async function delRule(tok){
  try{await api(`/api/rules/${encodeURIComponent(tok)}`,
                {method:'DELETE'});}
  catch(e){alert(e.message)}tick();}
async function eng(tok,op){
  try{await api(`/api/tenants/${encodeURIComponent(tok)}/engine/${op}`,
                {method:'POST'});}
  catch(e){alert(e.message)}tick();}
async function ckpt(){
  try{await api('/api/instance/checkpoint',{method:'POST'});}
  catch(e){alert(e.message)}tick();}
</script></body></html>
"""


def register_admin(router) -> None:
    """Serve the console at /admin (the page itself is public; every API
    call it makes carries the JWT it mints on sign-in)."""

    from sitewhere_tpu.web.pagejs import ESC_JS, MINT_JWT_JS

    page = _PAGE.replace("__SHARED_JS__", ESC_JS + MINT_JWT_JS)

    def admin_page(request):
        return 200, page.encode("utf-8"), "text/html; charset=utf-8"

    router.get("/admin", admin_page, auth=False)
