"""REST controllers: the reference's 27-controller surface on one router.

Reference: service-web-rest/src/main/java/com/sitewhere/web/rest/controllers/
(Devices.java, DeviceTypes.java, Assignments.java:98-160, Areas.java,
Zones.java, Customers.java, DeviceGroups.java, Assets.java, AssetTypes.java,
BatchOperations.java, Schedules.java, Tenants.java, Users.java,
DeviceEvents.java, DeviceStates.java, Instance.java, …). Each section below
names the controller it mirrors. Handlers receive a `Request` and return a
JSON-able object (or `(status, obj)`).

Tenant scoping: the reference resolves a tenant engine per request from the
X-SiteWhere-Tenant header via per-service gRPC routers; here `_engine()`
resolves the in-process TenantEngine the same way.
"""

from __future__ import annotations

import base64
import dataclasses
from typing import Any, Dict, List, Optional, Type

from sitewhere_tpu.errors import ErrorCode, NotFoundError, SiteWhereError
from sitewhere_tpu.model.area import (
    Area, AreaType, Customer, CustomerType, Zone)
from sitewhere_tpu.model.asset import Asset, AssetType
from sitewhere_tpu.model.batch import BatchOperation
from sitewhere_tpu.model.common import Location, new_id
from sitewhere_tpu.model.device import (
    Device, DeviceAlarm, DeviceAssignment, DeviceCommand, DeviceGroup,
    DeviceGroupElement, DeviceStatus, DeviceType)
from sitewhere_tpu.model.event import (
    AlertLevel, AlertSource, CommandInitiator, CommandTarget, DeviceAlert,
    DeviceCommandInvocation, DeviceCommandResponse, DeviceEventBatch,
    DeviceLocation, DeviceMeasurement, DeviceStateChange, DeviceStreamData)
from sitewhere_tpu.model.schedule import Schedule, ScheduledJob
from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.model.user import GrantedAuthority, SiteWhereRoles, User
from sitewhere_tpu.persist.event_management import EventIndex
from sitewhere_tpu.web.marshal import (
    entity_from_payload, results_to_jsonable, to_jsonable)
from sitewhere_tpu.web.router import Request, Router

_EVENT_ENUM_FIELDS = {
    "source": AlertSource, "level": AlertLevel,
    "initiator": CommandInitiator, "target": CommandTarget,
}


def event_from_payload(cls: Type, payload: Dict[str, Any]):
    """JSON body → DeviceEvent subclass (enum + base64 coercion)."""
    kwargs: Dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name not in payload or f.name == "event_type":
            continue
        val = payload[f.name]
        enum_cls = _EVENT_ENUM_FIELDS.get(f.name)
        if enum_cls is not None and val is not None:
            val = enum_cls[val] if isinstance(val, str) else enum_cls(val)
        if f.name == "data" and isinstance(val, str):
            val = base64.b64decode(val)
        kwargs[f.name] = val
    return cls(**kwargs)


def _body(request: Request) -> Dict[str, Any]:
    if not isinstance(request.body, dict):
        raise SiteWhereError("JSON object body required", http_status=400)
    return request.body


def register_all(router: Router, instance, server) -> None:
    REST = SiteWhereRoles.REST

    def _engine(request: Request):
        token = request.tenant or "default"
        tenant = instance.tenant_management.get_tenant_by_token(token)
        if tenant is None:
            raise NotFoundError(f"unknown tenant: {token}",
                                ErrorCode.INVALID_TENANT_TOKEN)
        # tenant access gate (reference: ITenant.getAuthorizedUserIds checked
        # by the tenant-token interceptors): a non-empty authorized list
        # restricts access to those users + tenant administrators.
        if (tenant.authorized_user_ids
                and request.username not in tenant.authorized_user_ids
                and SiteWhereRoles.ADMINISTER_TENANTS
                not in request.authorities):
            raise SiteWhereError(
                f"user not authorized for tenant {token}", http_status=403)
        engine = instance.get_tenant_engine(token)
        if engine is None:
            raise NotFoundError(f"tenant engine unavailable: {token}",
                                ErrorCode.INVALID_TENANT_TOKEN)
        return engine

    def _registry(request: Request):
        return _engine(request).registry

    def _events(request: Request):
        return _engine(request).event_management

    def _assignment_events(request: Request):
        return _events(request), request.params["token"]

    # ------------------------------------------------------------------
    # System / instance (reference: Instance.java, System info endpoints)
    # ------------------------------------------------------------------
    def get_version(request: Request):
        import sitewhere_tpu
        return {"version": sitewhere_tpu.__version__,
                "edition": "sitewhere-tpu"}

    def get_topology(request: Request):
        return instance.topology()

    def get_metrics(request: Request):
        return instance.metrics.report()

    def get_flight(request: Request):
        """GET /api/instance/flight — last-N step flight records (stage
        segment timelines on one monotonic clock) + window rollups
        (per-stage occupancy, sum-vs-max sync decomposition,
        h2d_overlap_fraction, critical-stage counts). See
        docs/OBSERVABILITY.md for the schema."""
        from sitewhere_tpu.runtime.flight import GLOBAL_FLIGHT
        last_n = request.query_int("last", 64)
        return GLOBAL_FLIGHT.export(last_n=max(1, min(last_n, 256)))

    def get_cluster_telemetry(request: Request):
        """GET /api/cluster/telemetry — cluster-wide telemetry fan-in:
        this host collects every peer's metrics snapshot, flight rollups,
        and event-age summary over busnet (`telemetry` op) and returns the
        peer-labeled merged view plus a merged Prometheus exposition
        (every sample re-labeled with peer="<pid>"). Unreachable peers are
        listed in `stale_peers` — a partial view beats a 502 during the
        exact incidents this endpoint exists for."""
        hooks = getattr(instance, "cluster_hooks", None)
        if hooks is None or not hasattr(hooks, "cluster_telemetry"):
            raise SiteWhereError(
                "cluster telemetry requires a cluster deployment "
                "(ClusterService or ControlPlaneCluster installed)",
                http_status=409)
        return hooks.cluster_telemetry()

    def get_logs(request: Request):
        return {"records": instance.log_aggregator.recent(
            limit=request.query_int("limit", 200),
            level=request.query_one("level"),
            source=request.query_one("source"))}

    def stream_topology(request: Request):
        """Live topology feed (SSE) — the reference's WebSocket
        TopologyBroadcaster. Emits a snapshot immediately, then again
        whenever it changes (0.5 s poll); keepalive comments every ~2 s of
        no change surface client disconnects (the write raises), so an
        abandoned stream never holds its server thread."""
        import json as _json
        import time as _time
        from sitewhere_tpu.web.server import SseStream

        max_s = min(float(request.query_one("max_seconds", "3600")), 3600.0)

        def events():
            last = None
            idle = 0
            deadline = _time.monotonic() + max_s
            while _time.monotonic() < deadline:
                snap = instance.topology()
                enc = _json.dumps(snap, sort_keys=True)
                if enc != last:
                    last = enc
                    idle = 0
                    yield snap
                else:
                    idle += 1
                    if idle % 4 == 0:
                        yield ": keepalive"
                _time.sleep(0.5)

        return SseStream(events())

    def get_configuration_model(request: Request):
        from sitewhere_tpu.runtime.config_model import (
            instance_configuration_model)
        return instance_configuration_model()

    def validate_configuration(request: Request):
        from sitewhere_tpu.runtime.config_model import validate_config
        issues = validate_config(_body(request))
        return {"valid": not issues,
                "issues": [i.to_json() for i in issues]}

    def get_openapi(request: Request):
        import sitewhere_tpu
        from sitewhere_tpu.web.openapi import generate_openapi
        return generate_openapi(router, version=sitewhere_tpu.__version__)

    # unauthenticated like the reference's swagger endpoint
    router.get("/api/openapi.json", get_openapi, auth=False)
    router.get("/api/system/version", get_version, authority=REST)
    router.get("/api/instance/topology", get_topology,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.get("/api/instance/metrics", get_metrics,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.get("/api/instance/flight", get_flight,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.get("/api/cluster/telemetry", get_cluster_telemetry,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.get("/api/instance/logs", get_logs,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.get("/api/instance/topology/stream", stream_topology,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.get("/api/instance/configuration/model", get_configuration_model,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.post("/api/instance/configuration/validate",
                validate_configuration,
                authority=SiteWhereRoles.VIEW_SERVER_INFO)

    def save_checkpoint(request: Request):
        """POST /api/instance/checkpoint — snapshot device state +
        interners + inbound cursors now (persist/checkpoint.py)."""
        manager = getattr(instance, "checkpoint_manager", None)
        if manager is None:
            raise SiteWhereError(
                "checkpointing requires a pipeline engine and a data_dir",
                http_status=409)
        path = manager.save()
        return {"path": path, "checkpoints": manager.list_checkpoints()}

    def list_checkpoints(request: Request):
        manager = getattr(instance, "checkpoint_manager", None)
        if manager is None:
            return {"checkpoints": []}
        return {"checkpoints": manager.list_checkpoints(),
                "restoredOffsets": manager.last_restore_offsets}

    # mutating + expensive (drains the engine, stalls the hot path,
    # writes to disk): requires the admin role like engine start/stop,
    # not the read-only VIEW_SERVER_INFO
    router.post("/api/instance/checkpoint", save_checkpoint,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)
    router.get("/api/instance/checkpoints", list_checkpoints,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)

    # ------------------------------------------------------------------
    # Serving tier — concurrent windowed analytics reads (serving/,
    # docs/SERVING.md). Every request goes through the QueryExecutor:
    # planner-routed host-vs-mesh replay behind the incremental grid
    # cache and per-tenant read admission — an over-budget poller gets
    # the structured 429 (QueryShedError) straight from submit.
    # ------------------------------------------------------------------
    def get_analytics_windows(request: Request):
        """GET /api/analytics/windows — per-device windowed stats for the
        request's tenant. `start_ms`+`end_ms` make the read cacheable
        (the grid origin is pinned); `keys` bounds the rows returned."""
        from sitewhere_tpu.serving import WindowQuery
        _engine(request)  # tenant existence + authorization gate
        query = WindowQuery(
            tenant=request.tenant or "default",
            window_ms=max(1, request.query_int("window_ms", 60_000)),
            mm_name=request.query_one("mm"),
            start_ms=(int(request.query_one("start_ms"))
                      if request.query_one("start_ms") is not None else None),
            end_ms=(int(request.query_one("end_ms"))
                    if request.query_one("end_ms") is not None else None),
            area_id=request.query_one("area"),
            max_windows=min(4096, request.query_int("max_windows", 1024)))
        served = instance.serving.query(query)
        report, span = served["report"], served["span"]
        max_keys = min(256, request.query_int("keys", 64))

        def _col(arr, row):
            # NaN/inf (empty windows) are not strict-JSON; clients get null
            return [v if v == v and abs(v) != float("inf") else None
                    for v in (float(x) for x in arr[row, :report.n_windows])]

        keys = []
        for row in range(min(report.num_keys, max_keys)):
            keys.append({
                "id": int(report.key_ids[row]),
                "token": report.key_tokens[row],
                "count": [int(c) for c in
                          report.stats.count[row, :report.n_windows]],
                "sum": _col(report.stats.sum, row),
                "mean": _col(report.stats.mean, row),
                "min": _col(report.stats.min, row),
                "max": _col(report.stats.max, row),
            })
        return {
            "t0_ms": int(report.t0_ms),
            "window_ms": int(report.window_ms),
            "n_windows": int(report.n_windows),
            "num_keys": report.num_keys,
            "keys": keys,
            "serving": {"route": span["route"],
                        "cache_hit": span["cache_hit"],
                        "est_rows": span["est_rows"],
                        "total_ms": span["total_ms"]},
        }

    def get_serving_report(request: Request):
        """GET /api/serving/report — the read-side flight plane: pool +
        admission state, cache residency/hit counters, recent spans."""
        return instance.serving.report()

    router.get("/api/analytics/windows", get_analytics_windows,
               authority=REST)
    router.get("/api/serving/report", get_serving_report,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)

    # ------------------------------------------------------------------
    # Rule management — the operator surface of the fused pipeline rules
    # (pipeline/engine.py add_threshold_rule/add_geofence_rule; reference:
    # service-rule-processing ZoneTestRuleProcessor.java:33 configured via
    # RuleProcessingParser spring config, here live CRUD over REST)
    # ------------------------------------------------------------------
    def _pipeline_engine():
        engine = instance.pipeline_engine
        if engine is None:
            raise SiteWhereError(
                "rule management requires a pipeline engine "
                "(pipeline.enabled)", http_status=409)
        return engine

    def list_pipeline_rules(request: Request):
        from sitewhere_tpu.pipeline.engine import rule_to_dict

        rules = _pipeline_engine().list_rules()
        out = {kind: [rule_to_dict(kind, rule) for rule in rule_list]
               for kind, rule_list in rules.items()}
        out["scripted"] = _list_scripted(request)
        return out

    def _scripted_rules(request: Request):
        """The REQUEST tenant's host-side rule processors (the scripted
        extension point; fused rules are instance-level)."""
        return _engine(request).rule_processors

    def create_pipeline_rule(request: Request):
        from sitewhere_tpu.pipeline.engine import rule_from_dict, rule_to_dict

        body = _body(request)
        if body.get("type") == "scripted":
            return _create_scripted_rule(request, body)
        engine = _pipeline_engine()
        kind, rule = rule_from_dict(body)
        from sitewhere_tpu.errors import DuplicateTokenError

        # one token namespace across fused AND scripted rules
        if _scripted_rules(request).get_processor(rule.token) is not None:
            raise DuplicateTokenError(f"rule '{rule.token}' already exists")
        engine.create_rule(kind, rule)  # atomic duplicate-token check
        return rule_to_dict(kind, rule)

    def _create_scripted_rule(request: Request, body: Dict):
        """Install a script-backed rule processor on the request tenant
        (the reference's Groovy rule processor, configured live instead
        of via spring restart). `script` names a ScriptManager script
        whose active version defines `process(context, event)` — verified
        at install time — and the resolve proxy hot-swaps on version
        activation. DURABLE and REPLICATED (round 5): the install records
        in the scripted-rule store (restored when the tenant engine
        boots, carried by the instance checkpoint) and gossips to every
        cluster host like a registry mutation."""
        from sitewhere_tpu.errors import DuplicateTokenError

        token = body.get("token") or ""
        script_id = body.get("script") or ""
        if not token or not script_id:
            raise SiteWhereError(
                "scripted rules require 'token' and 'script'",
                http_status=400)
        # one token namespace across fused AND scripted rules (the
        # scripted side's duplicate check is add_processor's atomic one,
        # inside install_scripted_rule)
        if instance.pipeline_engine is not None \
                and instance.pipeline_engine.get_rule(token)[0] is not None:
            raise DuplicateTokenError(f"rule '{token}' already exists")
        instance.install_scripted_rule(request.tenant or "default", token,
                                       script_id)
        return {"type": "scripted", "token": token, "script": script_id,
                "scope": "replicated"}

    def _list_scripted(request: Request):
        return [{"type": "scripted",
                 "token": host.processor.processor_id,
                 "script": getattr(host.processor, "script_id", ""),
                 "active": host.is_running()}
                for host in _scripted_rules(request).list_processors()]

    def get_pipeline_rule(request: Request):
        from sitewhere_tpu.pipeline.engine import rule_to_dict

        token = request.params["token"]
        kind, rule = _pipeline_engine().get_rule(token)
        if kind is None:
            processor = _scripted_rules(request).get_processor(token)
            if processor is not None:
                return {"type": "scripted", "token": token,
                        "script": getattr(processor, "script_id", ""),
                        "scope": "replicated"}
            raise NotFoundError(f"rule '{token}' not found",
                                ErrorCode.GENERIC)
        return rule_to_dict(kind, rule)

    def delete_pipeline_rule(request: Request):
        from sitewhere_tpu.pipeline.engine import rule_to_dict

        engine = _pipeline_engine()
        token = request.params["token"]
        kind, rule = engine.get_rule(token)
        if kind is None or not engine.remove_rule(token):
            if instance.remove_scripted_rule(request.tenant or "default",
                                             token):
                return {"type": "scripted", "token": token}
            raise NotFoundError(f"rule '{token}' not found",
                                ErrorCode.GENERIC)
        return rule_to_dict(kind, rule)

    router.get("/api/rules", list_pipeline_rules,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.post("/api/rules", create_pipeline_rule,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)
    router.get("/api/rules/{token}", get_pipeline_rule,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.delete("/api/rules/{token}", delete_pipeline_rule,
                  authority=SiteWhereRoles.ADMINISTER_TENANTS)

    # ------------------------------------------------------------------
    # Stateful rule programs — the CEP-lite compiler's tenant-scoped
    # control plane (rules/compiler.py, ops/stateful.py): composite,
    # temporal rules compiled to fixed-shape tables evaluated inside the
    # fused step. Installs are durable (RuleProgramStore), replicated
    # cluster-wide with the LWW/tombstone algebra, and carry per-program
    # fire/suppress counters read on demand from the rule state.
    # ------------------------------------------------------------------
    def _program_tenant(request: Request) -> str:
        # the path names the tenant; _engine() enforces existence + the
        # caller's tenant access like every other tenant-scoped route
        _engine(request)
        return request.params["token"]

    def list_rule_programs(request: Request):
        tenant = _program_tenant(request)
        engine = instance.pipeline_engine
        counters = (engine.rule_program_counters()
                    if engine is not None else {})
        out = []
        for row in instance.rule_programs.installs_for(tenant):
            spec = row["spec"]
            out.append({**spec,
                        **counters.get(spec.get("token", ""),
                                       {"fires": 0, "suppressed": 0})})
        return {"programs": out}

    def create_rule_program(request: Request):
        tenant = _program_tenant(request)
        return instance.install_rule_program(tenant, _body(request))

    def get_rule_program(request: Request):
        tenant = _program_tenant(request)
        token = request.params["program"]
        row = instance.rule_programs.get(tenant, token)
        if row is None:
            raise NotFoundError(f"rule program '{token}' not found",
                                ErrorCode.GENERIC)
        engine = instance.pipeline_engine
        counters = (engine.rule_program_counters()
                    if engine is not None else {})
        return {**row["spec"],
                **counters.get(token, {"fires": 0, "suppressed": 0})}

    def delete_rule_program(request: Request):
        tenant = _program_tenant(request)
        token = request.params["program"]
        if not instance.remove_rule_program(tenant, token):
            raise NotFoundError(f"rule program '{token}' not found",
                                ErrorCode.GENERIC)
        return {"token": token, "removed": True}

    router.get("/api/tenants/{token}/ruleprograms", list_rule_programs,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.post("/api/tenants/{token}/ruleprograms", create_rule_program,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)
    router.get("/api/tenants/{token}/ruleprograms/{program}",
               get_rule_program,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.delete("/api/tenants/{token}/ruleprograms/{program}",
                  delete_rule_program,
                  authority=SiteWhereRoles.ADMINISTER_TENANTS)

    # ------------------------------------------------------------------
    # Anomaly models — on-TPU inference control plane (ml/compiler.py,
    # ops/anomaly.py): tiny learned scorers compiled into replicated
    # weight tables and evaluated inside the fused step. Installs are
    # durable (ModelStore), replicated with the LWW/tombstone algebra,
    # and carry per-model fire/eval counters read on demand from the
    # model state.
    # ------------------------------------------------------------------
    def list_anomaly_models(request: Request):
        tenant = _program_tenant(request)
        engine = instance.pipeline_engine
        counters = (engine.anomaly_model_counters()
                    if engine is not None else {})
        out = []
        for row in instance.anomaly_models.installs_for(tenant):
            spec = row["spec"]
            out.append({**spec,
                        **counters.get(spec.get("token", ""),
                                       {"fires": 0, "evals": 0})})
        return {"models": out}

    def create_anomaly_model(request: Request):
        tenant = _program_tenant(request)
        return instance.install_anomaly_model(tenant, _body(request))

    def get_anomaly_model(request: Request):
        tenant = _program_tenant(request)
        token = request.params["model"]
        row = instance.anomaly_models.get(tenant, token)
        if row is None:
            raise NotFoundError(f"anomaly model '{token}' not found",
                                ErrorCode.GENERIC)
        engine = instance.pipeline_engine
        counters = (engine.anomaly_model_counters()
                    if engine is not None else {})
        return {**row["spec"],
                **counters.get(token, {"fires": 0, "evals": 0})}

    def delete_anomaly_model(request: Request):
        tenant = _program_tenant(request)
        token = request.params["model"]
        if not instance.remove_anomaly_model(tenant, token):
            raise NotFoundError(f"anomaly model '{token}' not found",
                                ErrorCode.GENERIC)
        return {"token": token, "removed": True}

    router.get("/api/tenants/{token}/models", list_anomaly_models,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.post("/api/tenants/{token}/models", create_anomaly_model,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)
    router.get("/api/tenants/{token}/models/{model}", get_anomaly_model,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.delete("/api/tenants/{token}/models/{model}",
                  delete_anomaly_model,
                  authority=SiteWhereRoles.ADMINISTER_TENANTS)

    # ------------------------------------------------------------------
    # Actuation policies — the alert -> command control plane
    # (actuation/compiler.py, ops/actuate.py): declarative policies
    # compiled into the fused step's slot table, evaluated right after
    # anomaly scoring, delivered through the tenant's command stack.
    # Installs are durable (ActuationPolicyStore), replicated with the
    # LWW/tombstone algebra, and carry live per-policy fire/debounce
    # counters read on demand from the actuation state.
    # ------------------------------------------------------------------
    def list_actuation_policies(request: Request):
        tenant = _program_tenant(request)
        engine = instance.pipeline_engine
        counters = (engine.actuation_policy_counters()
                    if engine is not None else {})
        out = []
        for row in instance.actuation_policies.installs_for(tenant):
            spec = row["spec"]
            out.append({**spec,
                        **counters.get(spec.get("token", ""),
                                       {"fires": 0, "debounced": 0})})
        return {"policies": out}

    def create_actuation_policy(request: Request):
        tenant = _program_tenant(request)
        return instance.install_actuation_policy(tenant, _body(request))

    def get_actuation_policy(request: Request):
        tenant = _program_tenant(request)
        token = request.params["policy"]
        row = instance.actuation_policies.get(tenant, token)
        if row is None:
            raise NotFoundError(f"actuation policy '{token}' not found",
                                ErrorCode.GENERIC)
        engine = instance.pipeline_engine
        counters = (engine.actuation_policy_counters()
                    if engine is not None else {})
        return {**row["spec"],
                **counters.get(token, {"fires": 0, "debounced": 0})}

    def delete_actuation_policy(request: Request):
        tenant = _program_tenant(request)
        token = request.params["policy"]
        if not instance.remove_actuation_policy(tenant, token):
            raise NotFoundError(f"actuation policy '{token}' not found",
                                ErrorCode.GENERIC)
        return {"token": token, "removed": True}

    router.get("/api/tenants/{token}/actuations", list_actuation_policies,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.post("/api/tenants/{token}/actuations", create_actuation_policy,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)
    router.get("/api/tenants/{token}/actuations/{policy}",
               get_actuation_policy,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.delete("/api/tenants/{token}/actuations/{policy}",
                  delete_actuation_policy,
                  authority=SiteWhereRoles.ADMINISTER_TENANTS)

    # ------------------------------------------------------------------
    # Prometheus exposition + on-demand device profiling (reference:
    # Dropwizard reporters, Microservice.java:146,244-246; Jaeger spans)
    # ------------------------------------------------------------------
    def metrics_prometheus(request: Request):
        """GET /metrics — Prometheus text format. Public like every
        scrape endpoint (operational counters only; front with a network
        policy if the deployment needs to). The derived-gauge assembly
        lives on the instance (extra_gauges) so the cluster telemetry
        fan-in serves the identical families per peer."""
        text = instance.prometheus_text()
        return 200, text.encode("utf-8"), "text/plain; version=0.0.4"

    def start_device_trace(request: Request):
        """POST /api/instance/trace/start {log_dir?} — begin an XLA
        profiler capture on the live engine (view with xprof/TensorBoard);
        idempotent while tracing."""
        engine = instance.pipeline_engine
        if engine is None:
            raise SiteWhereError("device tracing requires a pipeline "
                                 "engine", http_status=409)
        import os as _os

        body = request.body if isinstance(request.body, dict) else {}
        log_dir = (body.get("log_dir")
                   or _os.path.join(instance.data_dir or ".",
                                    "device-trace"))
        engine.start_device_trace(log_dir)
        return {"tracing": True, "log_dir": log_dir}

    def stop_device_trace(request: Request):
        engine = instance.pipeline_engine
        if engine is None:
            raise SiteWhereError("device tracing requires a pipeline "
                                 "engine", http_status=409)
        engine.stop_device_trace()
        return {"tracing": False}

    router.get("/metrics", metrics_prometheus, auth=False)
    router.post("/api/instance/trace/start", start_device_trace,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)
    router.post("/api/instance/trace/stop", stop_device_trace,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)

    # ------------------------------------------------------------------
    # Fault drills (runtime/faults.py; docs/OPERATIONS.md "Fault drills").
    # Arming is doubly guarded: admin authority AND the instance-level
    # allow_fault_drills switch — injecting faults is an operator drill
    # action, never something a stolen admin token should reach silently.
    # ------------------------------------------------------------------
    def _require_drills():
        if not getattr(instance, "allow_fault_drills", False):
            raise SiteWhereError(
                "fault drills are disabled on this instance "
                "(boot with allow_fault_drills=True)", http_status=403)

    def get_faults(request: Request):
        """GET /api/instance/faults — armed plan + per-point hit counts
        (empty report when disarmed)."""
        from sitewhere_tpu.runtime.faults import active_plan
        plan = active_plan()
        return {"armed": plan is not None,
                "plan": plan.report() if plan is not None else None}

    def arm_faults(request: Request):
        """POST /api/instance/faults {seed, rules: [{point, p?, times?,
        after?, delay_s?, duration_s?}]} — arm a seeded fault schedule."""
        _require_drills()
        from sitewhere_tpu.runtime.faults import FaultPlan, arm
        plan = FaultPlan.from_json(_body(request))
        arm(plan)
        return {"armed": True, "plan": plan.report()}

    def disarm_faults(request: Request):
        _require_drills()
        from sitewhere_tpu.runtime.faults import disarm
        disarm()
        return {"armed": False}

    router.get("/api/instance/faults", get_faults,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.post("/api/instance/faults", arm_faults,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)
    router.delete("/api/instance/faults", disarm_faults,
                  authority=SiteWhereRoles.ADMINISTER_TENANTS)

    # ------------------------------------------------------------------
    # Dead-letter operability (runtime/deadletter.py; reference: the
    # inbound-reprocess-events loop, KafkaTopicNaming.java:48-69)
    # ------------------------------------------------------------------
    def list_deadletters(request: Request):
        from sitewhere_tpu.runtime.deadletter import list_parked_topics
        return {"topics": list_parked_topics(instance.bus, instance.naming)}

    def read_deadletters(request: Request):
        from sitewhere_tpu.runtime.deadletter import read_parked_records
        topic = request.query_one("topic")
        if not topic:
            raise SiteWhereError("missing required query param 'topic'",
                                 http_status=400)
        return {"topic": topic, "records": read_parked_records(
            instance.bus, topic,
            limit=min(request.query_int("limit", 100), 1000))}

    def replay_deadletters(request: Request):
        from sitewhere_tpu.runtime.deadletter import replay_parked_records
        body = _body(request)
        topic = body.get("topic")
        if not topic:
            raise SiteWhereError("missing required body field 'topic'",
                                 http_status=400)
        return replay_parked_records(
            instance.bus, instance.naming, topic,
            target=body.get("target"),
            max_records=int(body.get("max", 65536)))

    router.get("/api/instance/deadletters", list_deadletters,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    router.get("/api/instance/deadletters/records", read_deadletters,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)
    # re-ingests data into the pipeline: admin-scoped like checkpoints
    router.post("/api/instance/deadletters/replay", replay_deadletters,
                authority=SiteWhereRoles.ADMINISTER_TENANTS)

    # ------------------------------------------------------------------
    # Script management (reference: Instance.java:304-560 scripting rpcs,
    # global + per-tenant scopes)
    # ------------------------------------------------------------------
    def _register_script_routes(prefix: str, scope_of) -> None:
        sm = instance.script_manager
        ADMIN = SiteWhereRoles.ADMINISTER_TENANTS

        def list_scripts(request: Request):
            return {"scripts": [i.to_json() for i in
                                sm.list_scripts(scope_of(request))]}

        def create_script(request: Request):
            body = _body(request)
            info = sm.create_script(
                scope_of(request), body["scriptId"], body.get("content", ""),
                name=body.get("name", ""),
                description=body.get("description", ""),
                activate=body.get("activate", True))
            return 201, info.to_json()

        def get_script(request: Request):
            return sm.get_script(scope_of(request),
                                 request.params["script_id"]).to_json()

        def delete_script(request: Request):
            sm.delete_script(scope_of(request), request.params["script_id"])
            return {"deleted": True}

        def get_version_content(request: Request):
            content = sm.get_content(scope_of(request),
                                     request.params["script_id"],
                                     request.params["version_id"])
            return {"content": content}

        def add_version(request: Request):
            body = _body(request)
            v = sm.add_version(scope_of(request),
                               request.params["script_id"],
                               body.get("content", ""),
                               comment=body.get("comment", ""),
                               activate=body.get("activate", False))
            return 201, v.to_json()

        def clone_version(request: Request):
            body = request.body if isinstance(request.body, dict) else {}
            v = sm.clone_version(scope_of(request),
                                 request.params["script_id"],
                                 request.params["version_id"],
                                 comment=body.get("comment", ""))
            return 201, v.to_json()

        def activate_version(request: Request):
            return sm.activate_version(scope_of(request),
                                       request.params["script_id"],
                                       request.params["version_id"]).to_json()

        base = f"{prefix}/scripting/scripts"
        router.get(base, list_scripts, authority=ADMIN)
        router.post(base, create_script, authority=ADMIN)
        router.get(base + "/{script_id}", get_script, authority=ADMIN)
        router.delete(base + "/{script_id}", delete_script, authority=ADMIN)
        router.get(base + "/{script_id}/versions/{version_id}/content",
                   get_version_content, authority=ADMIN)
        router.post(base + "/{script_id}/versions", add_version,
                    authority=ADMIN)
        router.post(base + "/{script_id}/versions/{version_id}/clone",
                    clone_version, authority=ADMIN)
        router.post(base + "/{script_id}/versions/{version_id}/activate",
                    activate_version, authority=ADMIN)

    from sitewhere_tpu.runtime.scripts import GLOBAL_SCOPE
    _register_script_routes("/api", lambda r: GLOBAL_SCOPE)
    _register_script_routes("/api/tenants/{token}",
                            lambda r: r.params["token"])

    # ------------------------------------------------------------------
    # Users + authorities (reference: Users.java, Authorities.java)
    # ------------------------------------------------------------------
    def _replication_status():
        """Cluster replication status of a provisioning mutation
        (multitenant/replication.py): did it broadcast, to how many
        peers, with how many publish failures parked for replay. Local
        (non-clustered) instances report mode "local"."""
        from sitewhere_tpu.multitenant.replication import replicator_of

        replicator = replicator_of(instance)
        if replicator is None:
            return {"mode": "local", "peers": 0}
        return replicator.status()

    def _with_replication(entity):
        payload = to_jsonable(entity)
        payload["replication"] = _replication_status()
        return payload

    def get_provisioning_status(request: Request):
        """GET /api/instance/provisioning — replication counters +
        tombstone count for the control-plane provisioning stream."""
        return _replication_status()

    router.get("/api/instance/provisioning", get_provisioning_status,
               authority=SiteWhereRoles.VIEW_SERVER_INFO)

    def create_user(request: Request):
        body = _body(request)
        password = body.pop("password", "")
        user = entity_from_payload(User, body)
        return 201, _with_replication(
            instance.user_management.create_user(user, password))

    def list_users(request: Request):
        return results_to_jsonable(
            instance.user_management.list_users(request.criteria()))

    def get_user(request: Request):
        user = instance.user_management.get_user_by_username(
            request.params["username"])
        if user is None:
            raise NotFoundError("unknown user", ErrorCode.INVALID_USERNAME)
        return user

    def update_user(request: Request):
        body = _body(request)
        password = body.pop("password", None)
        user = instance.user_management.update_user(
            request.params["username"], body, password=password)
        return _with_replication(user)

    def delete_user(request: Request):
        return _with_replication(instance.user_management.delete_user(
            request.params["username"]))

    def get_user_authorities(request: Request):
        return {"authorities": instance.user_management.get_user_authorities(
            request.params["username"])}

    def create_authority(request: Request):
        authority = entity_from_payload(GrantedAuthority, _body(request))
        return 201, instance.user_management.create_granted_authority(authority)

    def list_authorities(request: Request):
        return {"results": instance.user_management.list_granted_authorities()}

    ADMIN_USERS = SiteWhereRoles.ADMINISTER_USERS
    router.post("/api/users", create_user, authority=ADMIN_USERS)
    router.get("/api/users", list_users, authority=ADMIN_USERS)
    router.get("/api/users/{username}", get_user, authority=ADMIN_USERS)
    router.put("/api/users/{username}", update_user, authority=ADMIN_USERS)
    router.delete("/api/users/{username}", delete_user, authority=ADMIN_USERS)
    router.get("/api/users/{username}/authorities", get_user_authorities,
               authority=ADMIN_USERS)
    router.post("/api/authorities", create_authority, authority=ADMIN_USERS)
    router.get("/api/authorities", list_authorities, authority=ADMIN_USERS)

    # ------------------------------------------------------------------
    # Tenants + engine control (reference: Tenants.java)
    # ------------------------------------------------------------------
    ADMIN_TENANTS = SiteWhereRoles.ADMINISTER_TENANTS

    def create_tenant(request: Request):
        tenant = entity_from_payload(Tenant, _body(request))
        return 201, _with_replication(
            instance.tenant_management.create_tenant(tenant))

    def list_tenants(request: Request):
        return results_to_jsonable(
            instance.tenant_management.list_tenants(request.criteria()))

    def get_tenant(request: Request):
        tenant = instance.tenant_management.get_tenant_by_token(
            request.params["token"])
        if tenant is None:
            raise NotFoundError("unknown tenant",
                                ErrorCode.INVALID_TENANT_TOKEN)
        return tenant

    def update_tenant(request: Request):
        return _with_replication(instance.tenant_management.update_tenant(
            request.params["token"], _body(request)))

    def delete_tenant(request: Request):
        # retire (not admin-stop): deletion must not block a future
        # tenant that legitimately reuses the token after resurrection
        instance.engine_manager.retire_engine(request.params["token"])
        return _with_replication(instance.tenant_management.delete_tenant(
            request.params["token"]))

    def start_tenant_engine(request: Request):
        engine = instance.engine_manager.start_engine(request.params["token"],
                                                      force=True)
        if engine is None:
            raise NotFoundError("unknown tenant",
                                ErrorCode.INVALID_TENANT_TOKEN)
        return {"status": engine.status.name}

    def stop_tenant_engine(request: Request):
        instance.engine_manager.stop_engine(request.params["token"])
        return {"status": "STOPPED"}

    def restart_tenant_engine(request: Request):
        engine = instance.engine_manager.restart_engine(request.params["token"])
        return {"status": engine.status.name if engine else "FAILED"}

    router.post("/api/tenants", create_tenant, authority=ADMIN_TENANTS)
    router.get("/api/tenants", list_tenants, authority=ADMIN_TENANTS)
    router.get("/api/tenants/{token}", get_tenant, authority=ADMIN_TENANTS)
    router.put("/api/tenants/{token}", update_tenant, authority=ADMIN_TENANTS)
    router.delete("/api/tenants/{token}", delete_tenant,
                  authority=ADMIN_TENANTS)
    router.post("/api/tenants/{token}/engine/start", start_tenant_engine,
                authority=ADMIN_TENANTS)
    router.post("/api/tenants/{token}/engine/stop", stop_tenant_engine,
                authority=ADMIN_TENANTS)
    router.post("/api/tenants/{token}/engine/restart", restart_tenant_engine,
                authority=ADMIN_TENANTS)

    # ------------------------------------------------------------------
    # Device types + commands + statuses (reference: DeviceTypes.java)
    # ------------------------------------------------------------------
    def create_device_type(request: Request):
        return 201, _registry(request).create_device_type(
            entity_from_payload(DeviceType, _body(request)))

    def list_device_types(request: Request):
        return results_to_jsonable(
            _registry(request).list_device_types(request.criteria()))

    def get_device_type(request: Request):
        return _registry(request).get_device_type_by_token(
            request.params["token"])

    def update_device_type(request: Request):
        return _registry(request).update_device_type(
            request.params["token"], _body(request))

    def delete_device_type(request: Request):
        return _registry(request).delete_device_type(request.params["token"])

    def create_device_command(request: Request):
        registry = _registry(request)
        dtype = registry.get_device_type_by_token(request.params["token"])
        command = entity_from_payload(DeviceCommand, _body(request))
        command.device_type_id = dtype.id
        return 201, registry.create_device_command(command)

    def list_device_commands(request: Request):
        return results_to_jsonable(_registry(request).list_device_commands(
            device_type_token=request.params["token"]))

    def create_device_status(request: Request):
        registry = _registry(request)
        dtype = registry.get_device_type_by_token(request.params["token"])
        status = entity_from_payload(DeviceStatus, _body(request))
        status.device_type_id = dtype.id
        return 201, registry.create_device_status(status)

    def list_device_statuses(request: Request):
        return results_to_jsonable(_registry(request).list_device_statuses(
            device_type_token=request.params["token"]))

    router.post("/api/devicetypes", create_device_type, authority=REST)
    router.get("/api/devicetypes", list_device_types, authority=REST)
    router.get("/api/devicetypes/{token}", get_device_type, authority=REST)
    router.put("/api/devicetypes/{token}", update_device_type, authority=REST)
    router.delete("/api/devicetypes/{token}", delete_device_type,
                  authority=REST)
    router.post("/api/devicetypes/{token}/commands", create_device_command,
                authority=REST)
    router.get("/api/devicetypes/{token}/commands", list_device_commands,
               authority=REST)
    router.post("/api/devicetypes/{token}/statuses", create_device_status,
                authority=REST)
    router.get("/api/devicetypes/{token}/statuses", list_device_statuses,
               authority=REST)

    # ------------------------------------------------------------------
    # Devices (reference: Devices.java)
    # ------------------------------------------------------------------
    def create_device(request: Request):
        registry = _registry(request)
        body = _body(request)
        type_token = body.pop("device_type_token", None)
        device = entity_from_payload(Device, body)
        if type_token and not device.device_type_id:
            device.device_type_id = registry.get_device_type_by_token(
                type_token).id
        return 201, registry.create_device(device)

    def list_devices(request: Request):
        assigned = request.query_one("assigned")
        return results_to_jsonable(_registry(request).list_devices(
            request.criteria(),
            device_type_token=request.query_one("deviceType"),
            assigned=None if assigned is None else assigned == "true"))

    def get_device(request: Request):
        device = _registry(request).get_device_by_token(
            request.params["token"])
        if device is None:
            raise NotFoundError("unknown device",
                                ErrorCode.INVALID_DEVICE_TOKEN)
        return device

    def update_device(request: Request):
        return _registry(request).update_device(request.params["token"],
                                                _body(request))

    def delete_device(request: Request):
        return _registry(request).delete_device(request.params["token"])

    def list_device_assignments(request: Request):
        return results_to_jsonable(_registry(request).list_assignments(
            request.criteria(), device_token=request.params["token"]))

    def add_device_event_batch(request: Request):
        body = _body(request)
        batch = DeviceEventBatch(
            device_token=request.params["token"],
            measurements=[event_from_payload(DeviceMeasurement, e)
                          for e in body.get("measurements", [])],
            locations=[event_from_payload(DeviceLocation, e)
                       for e in body.get("locations", [])],
            alerts=[event_from_payload(DeviceAlert, e)
                    for e in body.get("alerts", [])])
        persisted = _events(request).add_device_event_batch(
            request.params["token"], batch)
        return 201, {"persisted": len(persisted)}

    def list_device_events(request: Request):
        return results_to_jsonable(_events(request).list_device_events(
            request.params["token"], request.date_criteria()))

    router.post("/api/devices", create_device, authority=REST)
    router.get("/api/devices", list_devices, authority=REST)
    router.get("/api/devices/{token}", get_device, authority=REST)
    router.put("/api/devices/{token}", update_device, authority=REST)
    router.delete("/api/devices/{token}", delete_device, authority=REST)
    router.get("/api/devices/{token}/assignments", list_device_assignments,
               authority=REST)
    def create_device_mapping(request: Request):
        """Map a child device into a composite parent's schema slot
        (Devices.java:268 addDeviceElementMapping)."""
        from sitewhere_tpu.model.device import DeviceElementMapping
        body = _body(request)
        mapping = DeviceElementMapping(
            device_element_schema_path=body.get(
                "deviceElementSchemaPath", body.get(
                    "device_element_schema_path", "")),
            device_token=body.get("deviceToken",
                                  body.get("device_token", "")))
        return _registry(request).create_device_element_mapping(
            request.params["token"], mapping)

    def delete_device_mapping(request: Request):
        """Remove the mapping at ?path= (Devices.java:281)."""
        path = request.query_one("path") or ""
        return _registry(request).delete_device_element_mapping(
            request.params["token"], path)

    router.post("/api/devices/{token}/events", add_device_event_batch,
                authority=REST)
    router.get("/api/devices/{token}/events", list_device_events,
               authority=REST)
    router.post("/api/devices/{token}/mappings", create_device_mapping,
                authority=REST)
    router.delete("/api/devices/{token}/mappings", delete_device_mapping,
                  authority=REST)

    # ------------------------------------------------------------------
    # Device alarms (reference: device-management alarm rpcs exposed
    # through Devices REST; DeviceAlarm CRUD + acknowledge/resolve)
    # ------------------------------------------------------------------
    def create_device_alarm(request: Request):
        registry = _registry(request)
        device = registry.get_device_by_token(request.params["token"])
        if device is None:
            raise NotFoundError("unknown device",
                                ErrorCode.INVALID_DEVICE_TOKEN)
        alarm = entity_from_payload(DeviceAlarm, _body(request))
        alarm.device_id = device.id
        return 201, registry.create_device_alarm(alarm)

    def list_device_alarms(request: Request):
        return results_to_jsonable(_registry(request).list_device_alarms(
            device_token=request.params["token"],
            criteria=request.criteria()))

    def list_all_alarms(request: Request):
        return results_to_jsonable(_registry(request).list_device_alarms(
            criteria=request.criteria()))

    def get_alarm(request: Request):
        alarm = _registry(request).get_device_alarm(
            request.params["alarm_id"])
        if alarm is None:
            raise NotFoundError("alarm not found",
                                ErrorCode.INVALID_EVENT_ID)
        return alarm

    def update_alarm(request: Request):
        return _registry(request).update_device_alarm(
            request.params["alarm_id"], _body(request))

    def delete_alarm(request: Request):
        return _registry(request).delete_device_alarm(
            request.params["alarm_id"])

    router.post("/api/devices/{token}/alarms", create_device_alarm,
                authority=REST)
    router.get("/api/devices/{token}/alarms", list_device_alarms,
               authority=REST)
    router.get("/api/alarms", list_all_alarms, authority=REST)
    router.get("/api/alarms/{alarm_id}", get_alarm, authority=REST)
    router.put("/api/alarms/{alarm_id}", update_alarm, authority=REST)
    router.delete("/api/alarms/{alarm_id}", delete_alarm, authority=REST)

    # ------------------------------------------------------------------
    # Label generation (reference: service-label-generation +
    # Devices.java/Assignments.java/... /{token}/label/{generatorId})
    # ------------------------------------------------------------------
    def list_label_generators(request: Request):
        return {"generators": instance.label_generators.generator_ids()}

    _LABEL_CODES = {
        "device": ErrorCode.INVALID_DEVICE_TOKEN,
        "devicetype": ErrorCode.INVALID_DEVICE_TYPE_TOKEN,
        "assignment": ErrorCode.INVALID_ASSIGNMENT_TOKEN,
        "area": ErrorCode.INVALID_AREA_TOKEN,
        "customer": ErrorCode.INVALID_CUSTOMER_TOKEN,
        "asset": ErrorCode.INVALID_ASSET_TOKEN,
    }

    def _label(entity_type: str, lookup):
        def handler(request: Request):
            token = request.params["token"]
            if lookup(request, token) is None:
                raise NotFoundError(f"unknown {entity_type}: {token}",
                                    _LABEL_CODES[entity_type])
            png = instance.label_generators.label_for(
                request.params["generator_id"], entity_type, token)
            return 200, png, "image/png"
        return handler

    router.get("/api/labels/generators", list_label_generators,
               authority=REST)
    for _etype, _pathseg, _lookup in (
            ("device", "devices",
             lambda r, t: _registry(r).get_device_by_token(t)),
            ("devicetype", "devicetypes",
             lambda r, t: _registry(r).get_device_type_by_token(t)),
            ("assignment", "assignments",
             lambda r, t: _registry(r).get_device_assignment_by_token(t)),
            ("area", "areas",
             lambda r, t: _registry(r).get_area_by_token(t)),
            ("customer", "customers",
             lambda r, t: _registry(r).get_customer_by_token(t)),
            ("asset", "assets",
             lambda r, t: _engine(r).asset_management.get_asset_by_token(t)),
    ):
        router.get(f"/api/{_pathseg}/{{token}}/label/{{generator_id}}",
                   _label(_etype, _lookup), authority=REST)

    # ------------------------------------------------------------------
    # Assignments + per-assignment events (reference: Assignments.java)
    # ------------------------------------------------------------------
    def create_assignment(request: Request):
        registry = _registry(request)
        body = _body(request)
        device_token = body.pop("device_token", None)
        assignment = entity_from_payload(DeviceAssignment, body)
        if device_token and not assignment.device_id:
            device = registry.get_device_by_token(device_token)
            if device is None:
                raise NotFoundError("unknown device",
                                    ErrorCode.INVALID_DEVICE_TOKEN)
            assignment.device_id = device.id
        for token_field, lookup, id_field in (
                ("area_token", registry.get_area_by_token, "area_id"),
                ("customer_token", registry.get_customer_by_token,
                 "customer_id")):
            tok = body.get(token_field)
            if tok and not getattr(assignment, id_field):
                setattr(assignment, id_field, lookup(tok).id)
        if not assignment.token:
            assignment.token = new_id()
        return 201, registry.create_device_assignment(assignment)

    def list_assignments(request: Request):
        return results_to_jsonable(_registry(request).list_assignments(
            request.criteria(), device_token=request.query_one("device"),
            customer_token=request.query_one("customer"),
            area_token=request.query_one("area")))

    def get_assignment(request: Request):
        assignment = _registry(request).get_device_assignment_by_token(
            request.params["token"])
        if assignment is None:
            raise NotFoundError("unknown assignment",
                                ErrorCode.INVALID_ASSIGNMENT_TOKEN)
        return assignment

    def release_assignment(request: Request):
        return _registry(request).release_device_assignment(
            request.params["token"])

    def mark_assignment_missing(request: Request):
        registry = _registry(request)
        assignment = registry.get_device_assignment_by_token(
            request.params["token"])
        if assignment is None:
            raise NotFoundError("unknown assignment",
                                ErrorCode.INVALID_ASSIGNMENT_TOKEN)
        return registry.mark_assignment_missing(assignment.id)

    router.post("/api/assignments", create_assignment, authority=REST)
    router.get("/api/assignments", list_assignments, authority=REST)
    router.get("/api/assignments/{token}", get_assignment, authority=REST)
    router.post("/api/assignments/{token}/end", release_assignment,
                authority=REST)
    router.post("/api/assignments/{token}/missing", mark_assignment_missing,
                authority=REST)

    def _event_routes(kind: str, cls, add_method: str, list_method: str):
        def add(request: Request):
            events_api, token = _assignment_events(request)
            payloads = request.body
            if isinstance(payloads, dict):
                payloads = [payloads]
            if not isinstance(payloads, list):
                raise SiteWhereError("JSON event body required",
                                     http_status=400)
            events = [event_from_payload(cls, p) for p in payloads]
            persisted = getattr(events_api, add_method)(token, *events)
            return 201, (persisted[0] if len(persisted) == 1
                         else {"persisted": len(persisted)})

        def list_(request: Request):
            events_api, token = _assignment_events(request)
            return results_to_jsonable(getattr(events_api, list_method)(
                EventIndex.ASSIGNMENT, token, request.date_criteria()))

        router.post(f"/api/assignments/{{token}}/{kind}", add, authority=REST)
        router.get(f"/api/assignments/{{token}}/{kind}", list_,
                   authority=REST)

    _event_routes("measurements", DeviceMeasurement, "add_measurements",
                  "list_measurements")
    _event_routes("locations", DeviceLocation, "add_locations",
                  "list_locations")
    _event_routes("alerts", DeviceAlert, "add_alerts", "list_alerts")
    _event_routes("statechanges", DeviceStateChange, "add_state_changes",
                  "list_state_changes")

    def create_command_invocation(request: Request):
        """POST …/invocations — the §3.4 cloud→device flow entry point."""
        events_api, token = _assignment_events(request)
        body = _body(request)
        invocation = event_from_payload(DeviceCommandInvocation, body)
        if not invocation.target_id:
            invocation.target_id = token
        if invocation.initiator == CommandInitiator.REST:
            invocation.initiator_id = request.username
        persisted = events_api.add_command_invocations(token, invocation)
        return 201, persisted[0]

    def list_command_invocations(request: Request):
        events_api, token = _assignment_events(request)
        return results_to_jsonable(events_api.list_command_invocations(
            EventIndex.ASSIGNMENT, token, request.date_criteria()))

    def create_command_response(request: Request):
        events_api, token = _assignment_events(request)
        response = event_from_payload(DeviceCommandResponse, _body(request))
        persisted = events_api.add_command_responses(token, response)
        return 201, persisted[0]

    def list_command_responses(request: Request):
        events_api, _ = _assignment_events(request)
        return results_to_jsonable(
            events_api.list_command_responses_for_invocation(
                request.params["invocation_id"], request.date_criteria()))

    router.post("/api/assignments/{token}/invocations",
                create_command_invocation, authority=REST)
    router.get("/api/assignments/{token}/invocations",
               list_command_invocations, authority=REST)
    router.post("/api/assignments/{token}/responses", create_command_response,
                authority=REST)
    router.get("/api/invocations/{invocation_id}/responses",
               list_command_responses, authority=REST)

    def list_assignment_events(request: Request):
        from sitewhere_tpu.persist.eventlog import EventFilter
        events_api, token = _assignment_events(request)
        return results_to_jsonable(events_api.log.query(
            events_api.tenant, EventFilter(assignment_token=token),
            request.date_criteria()))

    router.get("/api/assignments/{token}/events", list_assignment_events,
               authority=REST)

    # ------------------------------------------------------------------
    # Events by id (reference: DeviceEvents.java)
    # ------------------------------------------------------------------
    def get_event_by_id(request: Request):
        event = _events(request).get_event_by_id(request.params["event_id"])
        if event is None:
            raise NotFoundError("unknown event", ErrorCode.INVALID_EVENT_ID)
        return event

    def get_event_by_alternate_id(request: Request):
        event = _events(request).get_event_by_alternate_id(
            request.params["alternate_id"])
        if event is None:
            raise NotFoundError("unknown event", ErrorCode.INVALID_EVENT_ID)
        return event

    router.get("/api/events/id/{event_id}", get_event_by_id, authority=REST)
    router.get("/api/events/alternate/{alternate_id}",
               get_event_by_alternate_id, authority=REST)

    # ------------------------------------------------------------------
    # Areas / area types / zones (reference: Areas.java, Zones.java)
    # ------------------------------------------------------------------
    def create_area_type(request: Request):
        return 201, _registry(request).create_area_type(
            entity_from_payload(AreaType, _body(request)))

    def create_area(request: Request):
        return 201, _registry(request).create_area(
            entity_from_payload(Area, _body(request)))

    def list_areas(request: Request):
        return results_to_jsonable(
            _registry(request).list_areas(request.criteria()))

    def get_area(request: Request):
        return _registry(request).get_area_by_token(request.params["token"])

    def create_zone(request: Request):
        registry = _registry(request)
        area = registry.get_area_by_token(request.params["token"])
        zone = entity_from_payload(Zone, _body(request))
        zone.area_id = area.id
        return 201, registry.create_zone(zone)

    def list_zones(request: Request):
        return results_to_jsonable(_registry(request).list_zones(
            area_token=request.params["token"]))

    def get_zone(request: Request):
        return _registry(request).get_zone_by_token(request.params["token"])

    def update_zone(request: Request):
        body = _body(request)
        if "bounds" in body:
            body["bounds"] = [Location(**b) for b in body["bounds"]]
        return _registry(request).update_zone(request.params["token"], body)

    def delete_zone(request: Request):
        return _registry(request).delete_zone(request.params["token"])

    router.post("/api/areatypes", create_area_type, authority=REST)
    router.post("/api/areas", create_area, authority=REST)
    router.get("/api/areas", list_areas, authority=REST)
    router.get("/api/areas/{token}", get_area, authority=REST)
    router.post("/api/areas/{token}/zones", create_zone, authority=REST)
    router.get("/api/areas/{token}/zones", list_zones, authority=REST)
    router.get("/api/zones/{token}", get_zone, authority=REST)
    router.put("/api/zones/{token}", update_zone, authority=REST)
    router.delete("/api/zones/{token}", delete_zone, authority=REST)

    # ------------------------------------------------------------------
    # Customers (reference: Customers.java)
    # ------------------------------------------------------------------
    def create_customer_type(request: Request):
        return 201, _registry(request).create_customer_type(
            entity_from_payload(CustomerType, _body(request)))

    def create_customer(request: Request):
        return 201, _registry(request).create_customer(
            entity_from_payload(Customer, _body(request)))

    def list_customers(request: Request):
        return results_to_jsonable(
            _registry(request).list_customers(request.criteria()))

    def get_customer(request: Request):
        return _registry(request).get_customer_by_token(
            request.params["token"])

    router.post("/api/customertypes", create_customer_type, authority=REST)
    router.post("/api/customers", create_customer, authority=REST)
    router.get("/api/customers", list_customers, authority=REST)
    router.get("/api/customers/{token}", get_customer, authority=REST)

    # ------------------------------------------------------------------
    # Device groups (reference: DeviceGroups.java)
    # ------------------------------------------------------------------
    def create_device_group(request: Request):
        return 201, _registry(request).create_device_group(
            entity_from_payload(DeviceGroup, _body(request)))

    def get_device_group(request: Request):
        return _registry(request).get_device_group_by_token(
            request.params["token"])

    def add_group_elements(request: Request):
        payloads = request.body
        if isinstance(payloads, dict):
            payloads = [payloads]
        if not isinstance(payloads, list):
            raise SiteWhereError("JSON element body required",
                                 http_status=400)
        elements = [entity_from_payload(DeviceGroupElement, p)
                    for p in payloads]
        return 201, {"elements": _registry(request).add_device_group_elements(
            request.params["token"], elements)}

    def list_group_elements(request: Request):
        return results_to_jsonable(_registry(request)
                                   .list_device_group_elements(
                                       request.params["token"]))

    def list_group_devices(request: Request):
        return {"devices": _registry(request).expand_group_devices(
            request.params["token"])}

    router.post("/api/devicegroups", create_device_group, authority=REST)
    router.get("/api/devicegroups/{token}", get_device_group, authority=REST)
    router.post("/api/devicegroups/{token}/elements", add_group_elements,
                authority=REST)
    router.get("/api/devicegroups/{token}/elements", list_group_elements,
               authority=REST)
    router.get("/api/devicegroups/{token}/devices", list_group_devices,
               authority=REST)

    # ------------------------------------------------------------------
    # Assets (reference: Assets.java, AssetTypes.java)
    # ------------------------------------------------------------------
    def _assets(request: Request):
        return _engine(request).asset_management

    def create_asset_type(request: Request):
        return 201, _assets(request).create_asset_type(
            entity_from_payload(AssetType, _body(request)))

    def list_asset_types(request: Request):
        return results_to_jsonable(
            _assets(request).list_asset_types(request.criteria()))

    def get_asset_type(request: Request):
        return _assets(request).get_asset_type_by_token(
            request.params["token"])

    def create_asset(request: Request):
        assets = _assets(request)
        body = _body(request)
        type_token = body.pop("asset_type_token", None)
        asset = entity_from_payload(Asset, body)
        if type_token and not asset.asset_type_id:
            asset.asset_type_id = assets.get_asset_type_by_token(type_token).id
        return 201, assets.create_asset(asset)

    def list_assets(request: Request):
        return results_to_jsonable(_assets(request).list_assets(
            asset_type_token=request.query_one("assetType"),
            criteria=request.criteria()))

    def get_asset(request: Request):
        return _assets(request).get_asset_by_token(request.params["token"])

    def update_asset(request: Request):
        return _assets(request).update_asset(request.params["token"],
                                             _body(request))

    def delete_asset(request: Request):
        return _assets(request).delete_asset(request.params["token"])

    router.post("/api/assettypes", create_asset_type, authority=REST)
    router.get("/api/assettypes", list_asset_types, authority=REST)
    router.get("/api/assettypes/{token}", get_asset_type, authority=REST)
    router.post("/api/assets", create_asset, authority=REST)
    router.get("/api/assets", list_assets, authority=REST)
    router.get("/api/assets/{token}", get_asset, authority=REST)
    router.put("/api/assets/{token}", update_asset, authority=REST)
    router.delete("/api/assets/{token}", delete_asset, authority=REST)

    # ------------------------------------------------------------------
    # Batch operations (reference: BatchOperations.java)
    # ------------------------------------------------------------------
    def list_batch_operations(request: Request):
        return results_to_jsonable(
            _engine(request).batch_management.list_batch_operations(
                request.criteria()))

    def get_batch_operation(request: Request):
        return _engine(request).batch_management.get_batch_operation_by_token(
            request.params["token"])

    def list_batch_elements(request: Request):
        return results_to_jsonable(
            _engine(request).batch_management.list_batch_elements(
                request.params["token"], request.criteria()))

    def create_batch_command_invocation(request: Request):
        from sitewhere_tpu.batch.manager import \
            batch_command_invocation_request
        engine = _engine(request)
        body = _body(request)
        device_tokens = list(body.get("device_tokens", []))
        group_token = body.get("group_token")
        if group_token:
            device_tokens.extend(
                d.token for d in engine.registry.expand_group_devices(
                    group_token))
        operation = batch_command_invocation_request(
            command_token=body["command_token"],
            parameters=body.get("parameter_values", {}),
            device_tokens=device_tokens)
        operation = engine.batch_management.create_batch_operation(
            operation, engine.registry)
        engine.batch_manager.submit(operation)
        return 201, operation

    router.get("/api/batch", list_batch_operations, authority=REST)
    router.get("/api/batch/{token}", get_batch_operation, authority=REST)
    router.get("/api/batch/{token}/elements", list_batch_elements,
               authority=REST)
    router.post("/api/batch/command", create_batch_command_invocation,
                authority=REST)

    # ------------------------------------------------------------------
    # Schedules + jobs (reference: Schedules.java, ScheduledJobs.java)
    # ------------------------------------------------------------------
    ADMIN_SCHED = SiteWhereRoles.ADMINISTER_SCHEDULES

    def create_schedule(request: Request):
        return 201, _engine(request).schedule_management.create_schedule(
            entity_from_payload(Schedule, _body(request)))

    def list_schedules(request: Request):
        return results_to_jsonable(
            _engine(request).schedule_management.list_schedules(
                request.criteria()))

    def get_schedule(request: Request):
        return _engine(request).schedule_management.get_schedule_by_token(
            request.params["token"])

    def delete_schedule(request: Request):
        return _engine(request).schedule_management.delete_schedule(
            request.params["token"])

    def create_scheduled_job(request: Request):
        engine = _engine(request)
        job = entity_from_payload(ScheduledJob, _body(request))
        job = engine.schedule_management.create_scheduled_job(job)
        engine.schedule_manager.submit(job)
        return 201, job

    def list_scheduled_jobs(request: Request):
        return results_to_jsonable(
            _engine(request).schedule_management.list_scheduled_jobs(
                request.criteria()))

    def delete_scheduled_job(request: Request):
        engine = _engine(request)
        engine.schedule_manager.unschedule(request.params["token"])
        return engine.schedule_management.delete_scheduled_job(
            request.params["token"])

    router.post("/api/schedules", create_schedule, authority=ADMIN_SCHED)
    router.get("/api/schedules", list_schedules, authority=REST)
    router.get("/api/schedules/{token}", get_schedule, authority=REST)
    router.delete("/api/schedules/{token}", delete_schedule,
                  authority=ADMIN_SCHED)
    router.post("/api/jobs", create_scheduled_job, authority=ADMIN_SCHED)
    router.get("/api/jobs", list_scheduled_jobs, authority=REST)
    router.delete("/api/jobs/{token}", delete_scheduled_job,
                  authority=ADMIN_SCHED)

    # ------------------------------------------------------------------
    # Device streams (reference: Streams.java / service-streaming-media)
    # ------------------------------------------------------------------
    def create_device_stream(request: Request):
        body = _body(request)
        stream = _engine(request).streams.create_device_stream(
            request.params["token"], body["stream_id"],
            content_type=body.get("content_type",
                                  "application/octet-stream"))
        return 201, stream

    def list_device_streams(request: Request):
        return results_to_jsonable(_engine(request).streams
                                   .list_device_streams(
                                       request.params["token"],
                                       request.criteria()))

    def add_stream_data(request: Request):
        """Chunk upload: raw body bytes exactly as sent, sequence number in
        the path (JSON decoding must never touch chunk content)."""
        data = request.raw_body
        if not isinstance(data, bytes):
            raise SiteWhereError("binary body required", http_status=400)
        chunk = _engine(request).streams.add_stream_data(
            request.params["token"], request.params["stream_id"],
            int(request.params["sequence"]), data)
        return 201, {"id": chunk.id,
                     "sequence_number": chunk.sequence_number,
                     "size": len(data)}

    def get_stream_data(request: Request):
        streams = _engine(request).streams
        stream = streams.require_device_stream(request.params["token"],
                                               request.params["stream_id"])
        chunk = streams.get_stream_data(
            request.params["token"], request.params["stream_id"],
            int(request.params["sequence"]))
        if chunk is None:
            raise NotFoundError("unknown chunk", ErrorCode.INVALID_STREAM_ID)
        return 200, chunk.data, stream.content_type

    def get_stream_content(request: Request):
        streams = _engine(request).streams
        stream = streams.require_device_stream(request.params["token"],
                                               request.params["stream_id"])
        return 200, streams.reassemble(
            request.params["token"], request.params["stream_id"]), \
            stream.content_type

    router.post("/api/assignments/{token}/streams", create_device_stream,
                authority=REST)
    router.get("/api/assignments/{token}/streams", list_device_streams,
               authority=REST)
    router.post("/api/assignments/{token}/streams/{stream_id}/data/"
                "{sequence}", add_stream_data, authority=REST)
    router.get("/api/assignments/{token}/streams/{stream_id}/data/"
               "{sequence}", get_stream_data, authority=REST)
    router.get("/api/assignments/{token}/streams/{stream_id}/content",
               get_stream_content, authority=REST)

    # ------------------------------------------------------------------
    # Federated event search (reference: Search.java / service-event-search)
    # ------------------------------------------------------------------
    def list_search_providers(request: Request):
        return {"results": _engine(request).search_providers
                .list_providers()}

    def search_events(request: Request):
        from sitewhere_tpu.search import SearchCriteriaSpec
        spec = SearchCriteriaSpec.from_query(request)
        return results_to_jsonable(_engine(request).search_providers.search(
            request.params["provider_id"], spec))

    def search_raw(request: Request):
        """Engine-native query passthrough for EXTERNAL providers
        (Search.java searchDeviceEvents raw mode /
        executeQueryWithRawResponse)."""
        provider = _engine(request).search_providers.get_provider(
            request.params["provider_id"])
        raw = getattr(provider, "raw_query", None)
        if raw is None:
            raise SiteWhereError(
                f"provider '{provider.provider_id}' does not support raw "
                f"queries", http_status=400)
        return raw(request.query_one("q") or "")

    router.get("/api/search", list_search_providers, authority=REST)
    router.get("/api/search/{provider_id}/events", search_events,
               authority=REST)
    router.get("/api/search/{provider_id}/raw", search_raw,
               authority=REST)

    # ------------------------------------------------------------------
    # Device state (reference: DeviceStates.java) — reads the TPU-resident
    # per-device state tensors through the pipeline engine.
    # ------------------------------------------------------------------
    def get_device_state(request: Request):
        engine = instance.pipeline_engine
        if engine is None:
            raise SiteWhereError("pipeline engine not enabled",
                                 http_status=503)
        state = engine.get_device_state(request.params["token"])
        if state is None:
            raise NotFoundError("no state for device",
                                ErrorCode.INVALID_DEVICE_TOKEN)
        return state

    router.get("/api/devicestates/{token}", get_device_state, authority=REST)
