"""Search provider SPI + the built-in columnar provider.

Reference: service-event-search federates queries over external providers
behind ISearchProvider/IDeviceEventSearchProvider (search/solr/
SolrSearchProvider.java sends raw Solr queries). Here the SPI is the same
shape — named providers, criteria in, events out — but the shipped provider
queries the in-process columnar event log directly (no Solr sidecar), so
search is index-free and consistent with the hot path's storage. External
engines slot in as additional SearchProvider implementations.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, NotFoundError
from sitewhere_tpu.model.common import SearchCriteria, SearchResults
from sitewhere_tpu.model.event import DeviceEvent, DeviceEventType
from sitewhere_tpu.persist.eventlog import EventFilter
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent


@dataclass
class SearchCriteriaSpec:
    """Declarative event-search criteria (the REST query surface of the
    reference's searchDeviceEvents endpoint)."""

    event_type: Optional[DeviceEventType] = None
    device_token: Optional[str] = None
    assignment_token: Optional[str] = None
    measurement_name: Optional[str] = None
    start_date: Optional[int] = None
    end_date: Optional[int] = None
    page_number: int = 1
    page_size: int = 100

    def to_filter(self) -> EventFilter:
        return EventFilter(event_type=self.event_type,
                           device_token=self.device_token or None,
                           assignment_token=self.assignment_token or None,
                           mm_name=self.measurement_name or None,
                           start_date=self.start_date,
                           end_date=self.end_date)

    def to_criteria(self) -> SearchCriteria:
        return SearchCriteria(page_number=self.page_number,
                              page_size=self.page_size)

    @classmethod
    def from_query(cls, request) -> "SearchCriteriaSpec":
        """Build from a web Request's query params. Malformed values are the
        client's fault → 400, not 500."""
        from sitewhere_tpu.errors import SiteWhereError
        try:
            etype = request.query_one("eventType")
            dates = request.date_criteria()  # shared paging + date parsing
            return cls(
                event_type=(DeviceEventType[etype.upper()] if etype
                            else None),
                device_token=request.query_one("device"),
                assignment_token=request.query_one("assignment"),
                measurement_name=request.query_one("measurement"),
                start_date=dates.start_date,
                end_date=dates.end_date,
                page_number=dates.page_number,
                page_size=dates.page_size)
        except (KeyError, ValueError) as err:
            raise SiteWhereError(f"invalid search criteria: {err}",
                                 http_status=400)


class SearchProvider(LifecycleComponent):
    """Named search backend (ISearchProvider)."""

    def __init__(self, provider_id: str, name: str = ""):
        super().__init__(f"search-provider:{provider_id}")
        self.provider_id = provider_id
        self.provider_name = name or provider_id

    def search(self, spec: SearchCriteriaSpec) -> SearchResults[DeviceEvent]:
        raise NotImplementedError


class ColumnarSearchProvider(SearchProvider):
    """Event search straight off the columnar log (replaces the reference's
    Solr round-trip; same storage the TPU pipeline reads)."""

    def __init__(self, event_log, tenant: str = "default",
                 provider_id: str = "columnar"):
        super().__init__(provider_id, name="Columnar event search")
        self.log = event_log
        self.tenant = tenant

    def search(self, spec: SearchCriteriaSpec) -> SearchResults[DeviceEvent]:
        return self.log.query(self.tenant, spec.to_filter(),
                              spec.to_criteria())


class SearchProvidersManager(LifecycleComponent):
    """Registry of search providers for one tenant
    (SearchProvidersManager in the reference)."""

    def __init__(self, name: str = "search-providers"):
        super().__init__(name)
        self._providers: Dict[str, SearchProvider] = {}

    def register(self, provider: SearchProvider) -> SearchProvider:
        self._providers[provider.provider_id] = provider
        self.add_nested(provider)
        return provider

    def get_provider(self, provider_id: str) -> SearchProvider:
        provider = self._providers.get(provider_id)
        if provider is None:
            raise NotFoundError(f"unknown search provider: {provider_id}",
                                ErrorCode.GENERIC)
        return provider

    def list_providers(self) -> List[Dict[str, str]]:
        return [{"id": p.provider_id, "name": p.provider_name}
                for p in self._providers.values()]

    def search(self, provider_id: str, spec: SearchCriteriaSpec
               ) -> SearchResults[DeviceEvent]:
        return self.get_provider(provider_id).search(spec)
