"""Federated EXTERNAL event search over HTTP/JSON.

Reference: service-event-search federates queries to an external engine —
SolrSearchProvider.java sends the query to a Solr server and maps result
documents back to device events (executeQuery :125, raw passthrough
executeQueryWithRawResponse :149, geo getLocationsNear :175). The rebuild
keeps the in-process columnar provider as the default (providers.py), and
this provider fills the EXTERNAL slot: criteria become query parameters on
a configured HTTP endpoint, responses are JSON documents mapped to typed
events. stdlib urllib only — no client library to gate on.

Wire contract (the stub-server shape the tests pin):

  GET {base_url}/events?eventType=&device=&assignment=&measurement=
      &startDate=&endDate=&page=&pageSize=
    -> {"results": [<event doc>...], "total": N}
  GET {base_url}/raw?q=<query>           (raw passthrough, any JSON back)
  GET {base_url}/locations?latitude=&longitude=&distance=&pageSize=
    -> {"results": [<location doc>...], "total": N}

Event docs use the platform's own to_dict() form ("eventType" name or
"event_type" code); unknown fields are dropped (event_from_dict).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.common import SearchResults
from sitewhere_tpu.model.event import (
    DeviceEvent, DeviceEventType, DeviceLocation, event_from_dict)
from sitewhere_tpu.search.providers import (
    SearchCriteriaSpec, SearchProvider)


def _event_from_doc(doc: Dict[str, Any]) -> DeviceEvent:
    """External doc -> typed event: accept the enum NAME ("MEASUREMENT")
    or the packed integer code, like the platform's own payloads."""
    data = dict(doc)
    if "event_type" not in data:
        name = str(data.get("eventType", "MEASUREMENT")).upper()
        try:
            data["event_type"] = DeviceEventType[name].value
        except KeyError:
            raise SiteWhereError(
                f"external search document has unknown eventType {name!r}",
                ErrorCode.GENERIC, http_status=502)
    return event_from_dict(data)


class HttpSearchProvider(SearchProvider):
    """Named external search engine behind an HTTP/JSON endpoint (the
    SolrSearchProvider role, engine-agnostic)."""

    def __init__(self, provider_id: str, base_url: str, name: str = "",
                 timeout_s: float = 10.0,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(provider_id,
                         name=name or f"External search ({base_url})")
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.headers = dict(headers or {})

    # -- transport ---------------------------------------------------------
    def _get(self, path: str, params: Dict[str, Any]) -> Any:
        query = urllib.parse.urlencode(
            {k: v for k, v in params.items() if v not in (None, "")})
        url = f"{self.base_url}{path}"
        if query:
            url = f"{url}?{query}"
        req = urllib.request.Request(url, headers=self.headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as rsp:
                return json.loads(rsp.read().decode("utf-8"))
        except urllib.error.HTTPError as err:
            raise SiteWhereError(
                f"external search provider '{self.provider_id}' returned "
                f"HTTP {err.code}", ErrorCode.GENERIC,
                http_status=502) from err
        except (urllib.error.URLError, OSError, ValueError) as err:
            raise SiteWhereError(
                f"external search provider '{self.provider_id}' "
                f"unreachable: {err}", ErrorCode.GENERIC,
                http_status=502) from err

    # -- ISearchProvider operations ---------------------------------------
    def search(self, spec: SearchCriteriaSpec) -> SearchResults[DeviceEvent]:
        data = self._get("/events", {
            "eventType": spec.event_type.name if spec.event_type else None,
            "device": spec.device_token,
            "assignment": spec.assignment_token,
            "measurement": spec.measurement_name,
            "startDate": spec.start_date,
            "endDate": spec.end_date,
            "page": spec.page_number,
            "pageSize": spec.page_size,
        })
        docs = list(data.get("results", []))
        events = [_event_from_doc(d) for d in docs]
        return SearchResults(results=events,
                             num_results=int(data.get("total", len(events))))

    def raw_query(self, query: str) -> Any:
        """Engine-native query passthrough with the raw JSON response
        (executeQueryWithRawResponse parity)."""
        return self._get("/raw", {"q": query})

    def locations_near(self, latitude: float, longitude: float,
                       distance: float,
                       page_size: int = 100) -> List[DeviceLocation]:
        """Geo query (getLocationsNear parity)."""
        data = self._get("/locations", {
            "latitude": latitude, "longitude": longitude,
            "distance": distance, "pageSize": page_size})
        out: List[DeviceLocation] = []
        for doc in data.get("results", []):
            doc = dict(doc)
            doc.setdefault("eventType", "LOCATION")
            event = _event_from_doc(doc)
            if isinstance(event, DeviceLocation):
                out.append(event)
        return out
