"""Federated event search (reference: service-event-search)."""

from sitewhere_tpu.search.external import HttpSearchProvider
from sitewhere_tpu.search.providers import (
    ColumnarSearchProvider, SearchCriteriaSpec, SearchProvider,
    SearchProvidersManager)

__all__ = ["ColumnarSearchProvider", "HttpSearchProvider",
           "SearchCriteriaSpec", "SearchProvider", "SearchProvidersManager"]
