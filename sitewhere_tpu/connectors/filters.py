"""Outbound connector filters.

Reference: service-outbound-connectors filter/ — DeviceTypeFilter.java,
AreaFilter.java (include/exclude by entity), GroovyFilter (scripted). A
filter either includes (event passes only if it matches) or excludes
(event dropped if it matches).
"""

from __future__ import annotations

import enum
from typing import Callable, List

from sitewhere_tpu.model.event import DeviceEvent, DeviceEventContext


class FilterOperation(enum.Enum):
    INCLUDE = "include"
    EXCLUDE = "exclude"


class _MatchFilter:
    """Base: subclasses define `matches`; operation decides the gate."""

    def __init__(self, operation: FilterOperation = FilterOperation.INCLUDE):
        self.operation = operation

    def matches(self, context: DeviceEventContext,
                event: DeviceEvent) -> bool:
        raise NotImplementedError

    def accepts(self, context: DeviceEventContext,
                event: DeviceEvent) -> bool:
        matched = self.matches(context, event)
        return matched if self.operation == FilterOperation.INCLUDE \
            else not matched


class DeviceTypeFilter(_MatchFilter):
    """Match on the enriched context's device type id (DeviceTypeFilter.java).

    `registry` resolves type tokens to ids once at construction."""

    def __init__(self, registry, device_type_tokens: List[str],
                 operation: FilterOperation = FilterOperation.INCLUDE):
        super().__init__(operation)
        self.type_ids = {registry.get_device_type_by_token(t).id
                         for t in device_type_tokens}

    def matches(self, context, event) -> bool:
        return context.device_type_id in self.type_ids


class AreaFilter(_MatchFilter):
    """Match on the assignment's area (AreaFilter.java)."""

    def __init__(self, registry, area_tokens: List[str],
                 operation: FilterOperation = FilterOperation.INCLUDE):
        super().__init__(operation)
        self.area_ids = {registry.get_area_by_token(t).id
                         for t in area_tokens}

    def matches(self, context, event) -> bool:
        return context.area_id in self.area_ids


class EventTypeFilter(_MatchFilter):
    """Match on event type — common reference configuration pattern."""

    def __init__(self, event_types,
                 operation: FilterOperation = FilterOperation.INCLUDE):
        super().__init__(operation)
        self.event_types = set(event_types)

    def matches(self, context, event) -> bool:
        return event.event_type in self.event_types


class ScriptedFilter(_MatchFilter):
    """User callable `(context, event) -> bool` (GroovyFilter's extension
    point without a JVM)."""

    def __init__(self, script: Callable[[DeviceEventContext, DeviceEvent], bool],
                 operation: FilterOperation = FilterOperation.INCLUDE):
        super().__init__(operation)
        self.script = script

    def matches(self, context, event) -> bool:
        return bool(self.script(context, event))
