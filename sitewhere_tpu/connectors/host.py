"""Connector host: drives connectors from the enriched-events topic.

Reference: KafkaOutboundConnectorHost.java:44 — each IOutboundConnector is
wrapped in a host with its OWN consumer group (:86) reading
inbound-enriched-events, so connectors consume independently and a failed
connector replays from its own committed offset. The manager mirrors
OutboundConnectorsManager.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from sitewhere_tpu.connectors.base import OutboundConnector
from sitewhere_tpu.model.event import DeviceEvent, DeviceEventContext
from sitewhere_tpu.pipeline.enrichment import unpack_enriched
from sitewhere_tpu.runtime.bus import ConsumerHost, EventBus, Record, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

LOGGER = logging.getLogger("sitewhere.connectors")


class OutboundConnectorHost(LifecycleComponent):
    """One connector + one consumer group on the enriched topic."""

    def __init__(self, bus: EventBus, connector: OutboundConnector,
                 tenant: str = "default",
                 naming: Optional[TopicNaming] = None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"connector-host:{connector.connector_id}")
        self.bus = bus
        self.connector = connector
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.add_nested(connector)
        m = (metrics or MetricsRegistry()).scoped(
            f"connector.{connector.connector_id}")
        self.processed_meter = m.meter("processed")
        self.filtered_counter = m.counter("filtered")
        self.failed_counter = m.counter("failed")
        self._host = ConsumerHost(
            bus, self.naming.inbound_enriched_events(tenant),
            group_id=f"connector-{connector.connector_id}-{tenant}",
            handler=self.process)

    def on_start(self, monitor) -> None:
        self._host.start()

    def on_stop(self, monitor) -> None:
        self._host.stop()

    def process(self, records: List[Record]) -> None:
        """Decode + filter a poll batch, hand survivors to the connector
        (KafkaOutboundConnectorHost.java:173). Public for synchronous tests."""
        batch: List[Tuple[DeviceEventContext, DeviceEvent]] = []
        for record in records:
            try:
                context, event = unpack_enriched(record.value)
            except Exception:
                self.failed_counter.inc()
                continue
            if self.connector.accepts(context, event):
                batch.append((context, event))
            else:
                self.filtered_counter.inc()
        if batch:
            self.connector.process_batch(batch)
            self.processed_meter.mark(len(batch))


class OutboundConnectorsManager(LifecycleComponent):
    """Hosts all connectors of one tenant (OutboundConnectorsManager)."""

    def __init__(self, bus: EventBus, tenant: str = "default",
                 naming: Optional[TopicNaming] = None):
        super().__init__("outbound-connectors-manager")
        self.bus = bus
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.hosts: List[OutboundConnectorHost] = []

    def add_connector(self, connector: OutboundConnector) -> OutboundConnectorHost:
        host = OutboundConnectorHost(self.bus, connector, self.tenant,
                                     self.naming)
        self.hosts.append(host)
        self.add_nested(host)
        return host
