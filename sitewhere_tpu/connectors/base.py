"""Outbound connector SPI: enriched events -> external systems.

Reference: service-outbound-connectors — IOutboundConnector processes every
enriched event that passes its filters; implementations fan out to MQTT,
RabbitMQ, SQS, EventHub, InitialState, dweet.io, Solr. Events arrive in
batches (KafkaOutboundConnectorHost.java:173 hands the poll batch to a
processor), and each connector owns its consumer group so a slow sink never
backpressures the others.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Protocol, Tuple

from sitewhere_tpu.model.event import (
    DeviceAlert, DeviceCommandInvocation, DeviceCommandResponse, DeviceEvent,
    DeviceEventContext, DeviceLocation, DeviceMeasurement, DeviceStateChange,
    dispatch_event)
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent


class EventFilterProtocol(Protocol):
    """include/exclude gate (spi/connector/IDeviceEventFilter)."""

    def accepts(self, context: DeviceEventContext,
                event: DeviceEvent) -> bool: ...


class OutboundConnector(LifecycleComponent):
    """Base connector: override the per-type hooks or `process_batch` for
    bulk sinks (the reference's batch-capable connectors index whole
    batches at once)."""

    def __init__(self, connector_id: str,
                 filters: Optional[List[EventFilterProtocol]] = None):
        super().__init__(f"connector:{connector_id}")
        self.connector_id = connector_id
        self.filters = filters or []

    # -- filtering ---------------------------------------------------------
    def accepts(self, context: DeviceEventContext, event: DeviceEvent) -> bool:
        return all(f.accepts(context, event) for f in self.filters)

    # -- processing --------------------------------------------------------
    def process_batch(self, batch: List[Tuple[DeviceEventContext,
                                              DeviceEvent]]) -> None:
        """Default: dispatch each event to its typed hook."""
        for context, event in batch:
            dispatch_event(self, context, event)

    # typed no-op hooks (IOutboundConnector onMeasurements/onLocation/...)
    def on_measurement(self, context, event: DeviceMeasurement) -> None: ...
    def on_location(self, context, event: DeviceLocation) -> None: ...
    def on_alert(self, context, event: DeviceAlert) -> None: ...
    def on_command_invocation(self, context,
                              event: DeviceCommandInvocation) -> None: ...
    def on_command_response(self, context,
                            event: DeviceCommandResponse) -> None: ...
    def on_state_change(self, context, event: DeviceStateChange) -> None: ...
    def on_stream_data(self, context, event) -> None: ...
