"""Outbound connectors (reference: service-outbound-connectors)."""

from sitewhere_tpu.connectors.base import OutboundConnector
from sitewhere_tpu.connectors.filters import (
    AreaFilter, DeviceTypeFilter, EventTypeFilter, FilterOperation,
    ScriptedFilter)
from sitewhere_tpu.connectors.host import (
    OutboundConnectorHost, OutboundConnectorsManager)
from sitewhere_tpu.connectors.sinks import (
    CollectingConnector, DeviceEventMulticaster, DweetConnector,
    EventHubConnector, EventIndexConnector, HttpPostConnector,
    InitialStateConnector, MqttOutboundConnector, RabbitMqConnector,
    ScriptedConnector, SqsConnector, all_devices_of_type_route,
    event_to_json)

__all__ = [
    "AreaFilter", "CollectingConnector", "DeviceEventMulticaster",
    "DeviceTypeFilter", "DweetConnector", "EventHubConnector",
    "EventIndexConnector",
    "EventTypeFilter", "FilterOperation", "HttpPostConnector",
    "InitialStateConnector", "MqttOutboundConnector", "OutboundConnector",
    "RabbitMqConnector",
    "OutboundConnectorHost", "OutboundConnectorsManager",
    "ScriptedConnector", "ScriptedFilter", "SqsConnector",
    "all_devices_of_type_route", "event_to_json",
]
