"""Concrete outbound connectors.

Reference: service-outbound-connectors — MQTT (MqttOutboundConnector),
Solr indexing (solr/SolrOutboundConnector.java), Groovy scripted, the SaaS
sinks (dweet.io, InitialState — thin layers over HTTP POST here), AWS SQS
(gated on the optional boto3 client like the broker receivers in
sources/receivers_ext.py), plus multicasting with route builders
(spi/multicast/IDeviceEventMulticaster, groovy/routing/GroovyRouteBuilder).
"""

from __future__ import annotations

import json
import logging
from typing import Callable, Dict, List, Optional, Tuple

from sitewhere_tpu.connectors.base import OutboundConnector
from sitewhere_tpu.model.event import DeviceEvent, DeviceEventContext
from sitewhere_tpu.sources.receivers import EventLoopThread
from sitewhere_tpu.transport.mqtt import MqttClient

LOGGER = logging.getLogger("sitewhere.connectors")


def event_to_json(context: DeviceEventContext, event: DeviceEvent) -> bytes:
    payload = event.to_dict()
    payload["device"] = context.device_token
    payload["area"] = context.area_id
    payload["assignment"] = context.assignment_id
    return json.dumps(payload, default=str).encode("utf-8")


class MqttOutboundConnector(OutboundConnector):
    """Publish every accepted event as JSON to an MQTT topic; with a
    multicaster, to one topic per route (MqttOutboundConnector.java)."""

    def __init__(self, connector_id: str, host: str, port: int,
                 topic: str = "SW/outbound", filters=None,
                 multicaster: Optional["DeviceEventMulticaster"] = None,
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(connector_id, filters)
        self.host = host
        self.port = port
        self.topic = topic
        self.multicaster = multicaster
        self._loop_thread = loop_thread
        self._client: Optional[MqttClient] = None

    @property
    def loop_thread(self) -> EventLoopThread:
        if self._loop_thread is None:
            self._loop_thread = EventLoopThread.shared()
        return self._loop_thread

    def on_start(self, monitor) -> None:
        client = MqttClient(self.host, self.port,
                            client_id=f"connector-{self.connector_id}")
        self.loop_thread.run(client.connect())
        self._client = client

    def on_stop(self, monitor) -> None:
        if self._client is not None:
            self.loop_thread.run(self._client.disconnect())
            self._client = None

    def process_batch(self, batch: List[Tuple[DeviceEventContext,
                                              DeviceEvent]]) -> None:
        if self._client is None:
            raise RuntimeError(f"connector {self.connector_id} not started")
        for context, event in batch:
            payload = event_to_json(context, event)
            topics = ([r for r in self.multicaster.routes(context, event)]
                      if self.multicaster else [self.topic])
            for topic in topics:
                self.loop_thread.run(self._client.publish(topic, payload))


class ScriptedConnector(OutboundConnector):
    """User callable `(context, event) -> None` per event (Groovy connector
    extension point)."""

    def __init__(self, connector_id: str,
                 script: Callable[[DeviceEventContext, DeviceEvent], None],
                 filters=None):
        super().__init__(connector_id, filters)
        self.script = script

    @classmethod
    def from_manager(cls, connector_id: str, manager, script_id: str,
                     scope: str = "global", entry: str = "process",
                     filters=None) -> "ScriptedConnector":
        """Bind to a managed script's active version (runtime/scripts.py)."""
        return cls(connector_id, manager.resolve(scope, script_id, entry),
                   filters=filters)

    def process_batch(self, batch) -> None:
        for context, event in batch:
            self.script(context, event)


class EventIndexConnector(OutboundConnector):
    """Feed accepted events into an EventSearchIndex (search/index.py) —
    the role SolrOutboundConnector plays for the reference's event search."""

    def __init__(self, connector_id: str, index, filters=None):
        super().__init__(connector_id, filters)
        self.index = index

    def process_batch(self, batch) -> None:
        self.index.add_batch(batch)


class CollectingConnector(OutboundConnector):
    """Collect events in memory — test double and debugging tap."""

    def __init__(self, connector_id: str = "collector", filters=None):
        super().__init__(connector_id, filters)
        self.collected: List[Tuple[DeviceEventContext, DeviceEvent]] = []

    def process_batch(self, batch) -> None:
        self.collected.extend(batch)


class HttpPostConnector(OutboundConnector):
    """POST JSON events to an HTTP endpoint — the generic base the SaaS
    connectors below specialize via `_url_for`/`_post`."""

    def __init__(self, connector_id: str, url: str, filters=None,
                 timeout_s: float = 5.0):
        super().__init__(connector_id, filters)
        self.url = url
        self.timeout_s = timeout_s

    def _url_for(self, context: DeviceEventContext,
                 event: DeviceEvent) -> str:
        return self.url

    def _post(self, url: str, data: bytes,
              headers: Optional[Dict[str, str]] = None) -> None:
        import urllib.request
        request = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST")
        urllib.request.urlopen(request, timeout=self.timeout_s).read()

    def process_batch(self, batch) -> None:
        for context, event in batch:
            self._post(self._url_for(context, event),
                       event_to_json(context, event))


class DweetConnector(HttpPostConnector):
    """dweet.io connector (DweetIoConnector): each event posts to the
    per-thing dweet endpoint, the thing name defaulting to the device
    token."""

    def __init__(self, connector_id: str = "dweet", thing_prefix: str = "",
                 base_url: str = "https://dweet.io", filters=None,
                 timeout_s: float = 5.0):
        super().__init__(connector_id, base_url, filters=filters,
                         timeout_s=timeout_s)
        self.thing_prefix = thing_prefix

    def _url_for(self, context, event) -> str:
        from urllib.parse import quote
        thing = quote(f"{self.thing_prefix}{context.device_token}", safe="")
        return f"{self.url}/dweet/for/{thing}"


class InitialStateConnector(HttpPostConnector):
    """InitialState events-API connector (InitialStateEventProcessor): posts
    measurement values, location coordinates, and alert messages to a
    bucket keyed by the access-key header."""

    def __init__(self, connector_id: str = "initial-state",
                 streaming_access_key: str = "",
                 base_url: str = "https://groker.initialstate.com/api/events",
                 filters=None, timeout_s: float = 5.0):
        super().__init__(connector_id, base_url, filters=filters,
                         timeout_s=timeout_s)
        self.access_key = streaming_access_key

    @staticmethod
    def _line(context, event):
        name = getattr(event, "name", None) or event.event_type.name.lower()
        value = getattr(event, "value", None)
        if value is None and hasattr(event, "latitude"):
            value = f"{event.latitude},{event.longitude}"
        if value is None:  # alerts and other valueless events: string value
            value = getattr(event, "message", None) or \
                getattr(event, "type", None) or name
        return {"key": f"{context.device_token}.{name}", "value": value,
                "epoch": event.event_date / 1000.0}

    def process_batch(self, batch) -> None:
        lines = [self._line(context, event) for context, event in batch]
        if lines:
            self._post(self.url, json.dumps(lines).encode(),
                       headers={"X-IS-AccessKey": self.access_key,
                                "Accept-Version": "~0"})


class SqsConnector(OutboundConnector):
    """AWS SQS connector (SqsOutboundEventProcessor) over `boto3` when
    available (optional dependency — start() fails with a clear error
    otherwise, matching the receiver adapters in sources/receivers_ext.py).
    """

    def __init__(self, connector_id: str, queue_url: str, region: str =
                 "us-east-1", filters=None):
        super().__init__(connector_id, filters)
        self.queue_url = queue_url
        self.region = region
        self._client = None

    def on_start(self, monitor) -> None:
        from sitewhere_tpu.sources.receivers_ext import require_optional
        boto3 = require_optional("boto3", "AWS SQS")
        self._client = boto3.client("sqs", region_name=self.region)

    def process_batch(self, batch) -> None:
        for context, event in batch:
            self._client.send_message(
                QueueUrl=self.queue_url,
                MessageBody=event_to_json(context, event).decode())


class RabbitMqConnector(OutboundConnector):
    """RabbitMQ outbound sink (RabbitMqOutboundConnector.java): publish
    each accepted event as JSON to an exchange/routing key over `pika`
    when available (optional dependency — start() fails with a clear 501
    gating error otherwise, like the inbound AmqpEventReceiver)."""

    def __init__(self, connector_id: str, url: str = "amqp://localhost",
                 exchange: str = "", routing_key: str = "sitewhere.events",
                 durable: bool = False, filters=None,
                 multicaster: Optional["DeviceEventMulticaster"] = None):
        super().__init__(connector_id, filters)
        self.url = url
        self.exchange = exchange
        self.routing_key = routing_key
        self.durable = durable
        self.multicaster = multicaster
        self._connection = None
        self._channel = None

    def on_start(self, monitor) -> None:
        from sitewhere_tpu.sources.receivers_ext import require_optional
        pika = require_optional("pika", "RabbitMQ")
        self._connection = pika.BlockingConnection(
            pika.URLParameters(self.url))
        self._channel = self._connection.channel()
        if self.exchange:
            self._channel.exchange_declare(exchange=self.exchange,
                                           durable=self.durable)
        else:
            self._channel.queue_declare(queue=self.routing_key,
                                        durable=self.durable)

    def on_stop(self, monitor) -> None:
        if self._connection is not None:
            self._connection.close()
            self._connection = self._channel = None

    def process_batch(self, batch: List[Tuple[DeviceEventContext,
                                              DeviceEvent]]) -> None:
        if self._channel is None:
            raise RuntimeError(f"connector {self.connector_id} not started")
        for context, event in batch:
            payload = event_to_json(context, event)
            keys = (self.multicaster.routes(context, event)
                    if self.multicaster else [self.routing_key])
            for key in keys:
                self._channel.basic_publish(exchange=self.exchange,
                                            routing_key=key, body=payload)


class EventHubConnector(OutboundConnector):
    """Azure Event Hub outbound sink (EventHubOutboundConnector.java) over
    `azure-eventhub` when available (same optional-dependency gating as
    the inbound EventHubEventReceiver). Events batch per process_batch
    call — the hub client's native batching unit."""

    def __init__(self, connector_id: str, connection_str: str,
                 eventhub_name: str, filters=None):
        super().__init__(connector_id, filters)
        self.connection_str = connection_str
        self.eventhub_name = eventhub_name
        self._producer = None
        self._event_cls = None

    def on_start(self, monitor) -> None:
        from sitewhere_tpu.sources.receivers_ext import require_optional
        eventhub = require_optional("azure.eventhub", "Azure Event Hub")
        self._event_cls = eventhub.EventData
        self._producer = eventhub.EventHubProducerClient.from_connection_string(
            self.connection_str, eventhub_name=self.eventhub_name)

    def on_stop(self, monitor) -> None:
        if self._producer is not None:
            self._producer.close()
            self._producer = None

    def process_batch(self, batch: List[Tuple[DeviceEventContext,
                                              DeviceEvent]]) -> None:
        if self._producer is None:
            raise RuntimeError(f"connector {self.connector_id} not started")
        hub_batch = self._producer.create_batch()
        for context, event in batch:
            data = self._event_cls(event_to_json(context, event))
            try:
                hub_batch.add(data)
            except ValueError:
                # hub batch size limit (~1 MB): flush and keep going
                self._producer.send_batch(hub_batch)
                hub_batch = self._producer.create_batch()
                hub_batch.add(data)
        self._producer.send_batch(hub_batch)


class DeviceEventMulticaster:
    """Compute delivery routes per event (IDeviceEventMulticaster). Route
    builders are callables `(context, event) -> list[str]`
    (GroovyRouteBuilder's extension point)."""

    def __init__(self, builders: Optional[List[Callable[..., List[str]]]] = None):
        self.builders = builders or []

    def add_builder(self, builder: Callable[..., List[str]]) -> None:
        self.builders.append(builder)

    def routes(self, context: DeviceEventContext,
               event: DeviceEvent) -> List[str]:
        out: List[str] = []
        for builder in self.builders:
            out.extend(builder(context, event))
        return out


def all_devices_of_type_route(registry, device_type_token: str,
                              topic_pattern: str = "SW/{token}/broadcast"
                              ) -> Callable[..., List[str]]:
    """AllWithSpecificationStringMulticaster: route an event to a topic per
    device of the given type."""
    def builder(context: DeviceEventContext, event: DeviceEvent) -> List[str]:
        device_type = registry.get_device_type_by_token(device_type_token)
        return [topic_pattern.format(token=d.token)
                for d in registry.devices.all()
                if d.device_type_id == device_type.id]
    return builder
