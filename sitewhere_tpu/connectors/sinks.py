"""Concrete outbound connectors.

Reference: service-outbound-connectors — MQTT (MqttOutboundConnector),
Solr indexing (solr/SolrOutboundConnector.java), Groovy scripted, plus
multicasting with route builders (spi/multicast/IDeviceEventMulticaster,
groovy/routing/GroovyRouteBuilder). Cloud-vendor sinks (SQS/EventHub/
InitialState/dweet.io) are network clients the image can't reach; their
role — JSON-serialized event POST to an external endpoint — is covered by
HttpPostConnector against any URL.
"""

from __future__ import annotations

import json
import logging
from typing import Callable, Dict, List, Optional, Tuple

from sitewhere_tpu.connectors.base import OutboundConnector
from sitewhere_tpu.model.event import DeviceEvent, DeviceEventContext
from sitewhere_tpu.sources.receivers import EventLoopThread
from sitewhere_tpu.transport.mqtt import MqttClient

LOGGER = logging.getLogger("sitewhere.connectors")


def event_to_json(context: DeviceEventContext, event: DeviceEvent) -> bytes:
    payload = event.to_dict()
    payload["device"] = context.device_token
    payload["area"] = context.area_id
    payload["assignment"] = context.assignment_id
    return json.dumps(payload, default=str).encode("utf-8")


class MqttOutboundConnector(OutboundConnector):
    """Publish every accepted event as JSON to an MQTT topic; with a
    multicaster, to one topic per route (MqttOutboundConnector.java)."""

    def __init__(self, connector_id: str, host: str, port: int,
                 topic: str = "SW/outbound", filters=None,
                 multicaster: Optional["DeviceEventMulticaster"] = None,
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__(connector_id, filters)
        self.host = host
        self.port = port
        self.topic = topic
        self.multicaster = multicaster
        self._loop_thread = loop_thread
        self._client: Optional[MqttClient] = None

    @property
    def loop_thread(self) -> EventLoopThread:
        if self._loop_thread is None:
            self._loop_thread = EventLoopThread.shared()
        return self._loop_thread

    def on_start(self, monitor) -> None:
        client = MqttClient(self.host, self.port,
                            client_id=f"connector-{self.connector_id}")
        self.loop_thread.run(client.connect())
        self._client = client

    def on_stop(self, monitor) -> None:
        if self._client is not None:
            self.loop_thread.run(self._client.disconnect())
            self._client = None

    def process_batch(self, batch: List[Tuple[DeviceEventContext,
                                              DeviceEvent]]) -> None:
        if self._client is None:
            raise RuntimeError(f"connector {self.connector_id} not started")
        for context, event in batch:
            payload = event_to_json(context, event)
            topics = ([r for r in self.multicaster.routes(context, event)]
                      if self.multicaster else [self.topic])
            for topic in topics:
                self.loop_thread.run(self._client.publish(topic, payload))


class ScriptedConnector(OutboundConnector):
    """User callable `(context, event) -> None` per event (Groovy connector
    extension point)."""

    def __init__(self, connector_id: str,
                 script: Callable[[DeviceEventContext, DeviceEvent], None],
                 filters=None):
        super().__init__(connector_id, filters)
        self.script = script

    @classmethod
    def from_manager(cls, connector_id: str, manager, script_id: str,
                     scope: str = "global", entry: str = "process",
                     filters=None) -> "ScriptedConnector":
        """Bind to a managed script's active version (runtime/scripts.py)."""
        return cls(connector_id, manager.resolve(scope, script_id, entry),
                   filters=filters)

    def process_batch(self, batch) -> None:
        for context, event in batch:
            self.script(context, event)


class EventIndexConnector(OutboundConnector):
    """Feed accepted events into an EventSearchIndex (search/index.py) —
    the role SolrOutboundConnector plays for the reference's event search."""

    def __init__(self, connector_id: str, index, filters=None):
        super().__init__(connector_id, filters)
        self.index = index

    def process_batch(self, batch) -> None:
        self.index.add_batch(batch)


class CollectingConnector(OutboundConnector):
    """Collect events in memory — test double and debugging tap."""

    def __init__(self, connector_id: str = "collector", filters=None):
        super().__init__(connector_id, filters)
        self.collected: List[Tuple[DeviceEventContext, DeviceEvent]] = []

    def process_batch(self, batch) -> None:
        self.collected.extend(batch)


class HttpPostConnector(OutboundConnector):
    """POST JSON events to an HTTP endpoint — the shape of the reference's
    InitialState/dweet.io connectors, target-agnostic."""

    def __init__(self, connector_id: str, url: str, filters=None,
                 timeout_s: float = 5.0):
        super().__init__(connector_id, filters)
        self.url = url
        self.timeout_s = timeout_s

    def process_batch(self, batch) -> None:
        import urllib.request
        for context, event in batch:
            request = urllib.request.Request(
                self.url, data=event_to_json(context, event),
                headers={"Content-Type": "application/json"}, method="POST")
            urllib.request.urlopen(request, timeout=self.timeout_s).read()


class DeviceEventMulticaster:
    """Compute delivery routes per event (IDeviceEventMulticaster). Route
    builders are callables `(context, event) -> list[str]`
    (GroovyRouteBuilder's extension point)."""

    def __init__(self, builders: Optional[List[Callable[..., List[str]]]] = None):
        self.builders = builders or []

    def add_builder(self, builder: Callable[..., List[str]]) -> None:
        self.builders.append(builder)

    def routes(self, context: DeviceEventContext,
               event: DeviceEvent) -> List[str]:
        out: List[str] = []
        for builder in self.builders:
            out.extend(builder(context, event))
        return out


def all_devices_of_type_route(registry, device_type_token: str,
                              topic_pattern: str = "SW/{token}/broadcast"
                              ) -> Callable[..., List[str]]:
    """AllWithSpecificationStringMulticaster: route an event to a topic per
    device of the given type."""
    def builder(context: DeviceEventContext, event: DeviceEvent) -> List[str]:
        device_type = registry.get_device_type_by_token(device_type_token)
        return [topic_pattern.format(token=d.token)
                for d in registry.devices.all()
                if d.device_type_id == device_type.id]
    return builder
