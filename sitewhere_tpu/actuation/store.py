"""Durable actuation-policy install registry.

The control-plane twin of ml/store.py's ModelStore for the compiled
alert->command policies (actuation/compiler.py): (tenant, token) ->
{spec, stamp}; JSON-durable, last-writer-wins with removal tombstones,
so installs survive restarts, ride the instance checkpoint, and
replicate cluster-wide under gossip kind `_actuation_policy` with the
same LWW/tombstone algebra the provisioning replicator uses
(multitenant/replication.py).

The payload is the whole normalized spec — the payload IS the identity:
appliers are idempotent and order-free, and the LWW tiebreak on equal
stamps compares the spec's canonical JSON so every host converges on
the same winner.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.model.common import now_ms

LOGGER = logging.getLogger("sitewhere.actuation.store")


class ActuationPolicyStore:
    """(tenant, token) -> {spec, stamp}; JSON-durable, LWW, with removal
    tombstones (see module docstring)."""

    def __init__(self, data_dir: Optional[str] = None):
        self._path = (os.path.join(data_dir, "actuation_policies.json")
                      if data_dir else None)
        self._lock = threading.Lock()
        # (tenant, token) -> {"spec": dict, "stamp": int}
        self._installs: Dict[tuple, Dict] = {}
        self._tombstones: Dict[tuple, int] = {}
        self._listeners: List[Callable] = []
        self._load()

    # -- durability --------------------------------------------------------
    def _load(self) -> None:
        if not self._path or not os.path.exists(self._path):
            return
        try:
            with open(self._path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            LOGGER.exception("unreadable actuation-policy store %s",
                             self._path)
            return
        for row in data.get("installs", []):
            self._installs[(row["tenant"], row["token"])] = {
                "spec": row["spec"], "stamp": int(row.get("stamp", 0))}
        for row in data.get("tombstones", []):
            self._tombstones[(row["tenant"], row["token"])] = int(
                row.get("stamp", 0))

    def _sync(self) -> None:
        if not self._path:
            return
        data = {
            "installs": [{"tenant": t, "token": k, **v}
                         for (t, k), v in sorted(self._installs.items())],
            "tombstones": [{"tenant": t, "token": k, "stamp": s}
                           for (t, k), s in sorted(self._tombstones.items())],
        }
        tmp = f"{self._path}.{os.getpid()}.tmp"
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh)
        os.replace(tmp, self._path)

    # -- replication surface ----------------------------------------------
    def add_listener(self, fn: Callable) -> None:
        """fn(op: "add"|"remove", tenant, token, payload) — fired on LOCAL
        mutations only (record/erase, not apply_*)."""
        self._listeners.append(fn)

    def _notify(self, op: str, tenant: str, token: str, payload) -> None:
        for fn in list(self._listeners):
            try:
                fn(op, tenant, token, payload)
            except Exception:
                LOGGER.exception(
                    "actuation-policy listener failed (%s %s/%s)",
                    op, tenant, token)

    # -- mutations ---------------------------------------------------------
    def record(self, tenant: str, token: str, spec: Dict,
               notify: bool = True) -> Dict:
        """Local install; returns the payload the gossip side publishes.
        ``notify=False`` defers the listener fire to the caller (`emit`)
        — same deferred-publish contract as the rule/model stores."""
        with self._lock:
            stamp = max(now_ms(),
                        self._tombstones.get((tenant, token), -1) + 1,
                        self._installs.get((tenant, token),
                                           {"stamp": -1})["stamp"] + 1)
            payload = {"spec": dict(spec), "stamp": stamp}
            self._installs[(tenant, token)] = payload
            self._tombstones.pop((tenant, token), None)
            self._sync()
        if notify:
            self._notify("add", tenant, token, payload)
        return payload

    def erase(self, tenant: str, token: str,
              notify: bool = True) -> Optional[int]:
        """Local removal; returns the tombstone stamp (None if unknown)."""
        with self._lock:
            existing = self._installs.pop((tenant, token), None)
            if existing is None:
                return None
            stamp = max(now_ms(), existing["stamp"] + 1)
            self._tombstones[(tenant, token)] = stamp
            self._sync()
        if notify:
            self._notify("remove", tenant, token, stamp)
        return stamp

    def emit(self, op: str, tenant: str, token: str, payload) -> None:
        """Deferred listener fire for record/erase with notify=False —
        call OUTSIDE any lock (listeners publish to peer bus edges)."""
        self._notify(op, tenant, token, payload)

    @staticmethod
    def _spec_key(spec: Dict) -> str:
        return json.dumps(spec, sort_keys=True, separators=(",", ":"))

    def _add_wins_locked(self, key: tuple, spec: Dict, stamp: int) -> bool:
        if stamp <= self._tombstones.get(key, -1):
            return False
        local = self._installs.get(key)
        return local is None or (
            (local["stamp"], self._spec_key(local["spec"]))
            < (stamp, self._spec_key(spec)))

    def would_apply_add(self, tenant: str, token: str, spec: Dict,
                        stamp: int) -> bool:
        """Non-mutating LWW check: lets the caller attach the live policy
        BEFORE committing the store (an attach that fails must leave the
        store unchanged so redelivery retries cleanly)."""
        with self._lock:
            return self._add_wins_locked((tenant, token), spec, stamp)

    def apply_add(self, tenant: str, token: str, spec: Dict,
                  stamp: int) -> bool:
        """Replicated install: LWW against local install/tombstone;
        idempotent, never notifies. Returns True when it newly wins."""
        with self._lock:
            key = (tenant, token)
            if not self._add_wins_locked(key, spec, stamp):
                return False
            self._installs[key] = {"spec": dict(spec), "stamp": stamp}
            self._tombstones.pop(key, None)
            self._sync()
            return True

    def apply_remove(self, tenant: str, token: str, stamp: int) -> bool:
        with self._lock:
            key = (tenant, token)
            local = self._installs.get(key)
            if local is not None and local["stamp"] > stamp:
                return False
            self._tombstones[key] = max(stamp,
                                        self._tombstones.get(key, -1))
            if local is None:
                # durable tombstone even with nothing to remove: a remove
                # arriving before its add must survive a restart or the
                # redelivered older add resurrects the policy here
                self._sync()
                return False
            del self._installs[key]
            self._sync()
            return True

    # -- reads -------------------------------------------------------------
    def installs_for(self, tenant: str) -> List[Dict]:
        with self._lock:
            return [{"token": token, "spec": dict(v["spec"]),
                     "stamp": v["stamp"]}
                    for (t, token), v in sorted(self._installs.items())
                    if t == tenant]

    def all_installs(self) -> List[Dict]:
        with self._lock:
            return [{"tenant": t, "token": token, "spec": dict(v["spec"]),
                     "stamp": v["stamp"]}
                    for (t, token), v in sorted(self._installs.items())]

    def get(self, tenant: str, token: str) -> Optional[Dict]:
        with self._lock:
            v = self._installs.get((tenant, token))
            return {"spec": dict(v["spec"]), "stamp": v["stamp"]} \
                if v else None

    def export_state(self) -> Dict:
        """Checkpoint payload (installs only; tombstones are a gossip
        convergence aid, not durable state worth moving cross-topology)."""
        with self._lock:
            return {"installs": [{"tenant": t, "token": k, **v}
                                 for (t, k), v in
                                 sorted(self._installs.items())]}
