"""Actuation-policy compiler: declarative alert->command policies ->
fixed-shape SoA tables.

ROADMAP item 5 (closing the loop): the fused step already compacts every
rule/program/model fire into the alert lanes; this module compiles
per-tenant JSON policies — "when THIS kind of alert fires at or above
THIS level, send THIS command with THESE params, at most once per
debounce window per device" — into a static table that ops/actuate.py
evaluates for every (batch row, policy) pair INSIDE the fused step, so
detection->actuation never leaves the device until the compacted command
lane ships in the same materialize fetch pass as the alerts.

Like rules/compiler.py and ml/compiler.py, everything pads to static
buckets (one cached jit program per bucket shape); installing or
removing a policy only rewrites table rows, and a replace bumps the
slot's epoch so per-(device, policy) debounce state lazily resets
inside the jit (the shared generation trick).

Spec shape (JSON):

    {"token": "overheat-shutdown", "tenant_token": "acme",
     "source": "threshold",       # any|threshold|geofence|program|model
     "match_slot": -1,            # rule idx / program slot / model slot;
                                  # -1 = any slot of the source kind
     "min_level": "WARNING",      # fire only at alert level >= this
     "debounce_ms": 60000,        # per-(device, policy) refractory window
     "command": "shutdown",       # command token delivered to the device
     "params": [1, 0],            # up to 4 int32 params (zero padded)
     "active": true}

Matching semantics (ops/actuate.py pins them with a NumPy oracle in
tests/test_actuation.py): a policy matches a batch row when any allowed
source kind fired on that row with a matching slot id and a level >=
min_level; per device the policy triggers on its LAST matching row of
the step (one command per (device, policy) per step max), gated by the
debounce window measured in event time against the stored last-fire ts.

Validation is structural and loud: an invalid spec raises
ActuationPolicyError (a 409 SiteWhereError) naming the offending field
path ("params[2]"), never a stack trace — on both the REST and the
replicated-apply paths.
"""

from __future__ import annotations

from typing import Dict

import numpy as np
from flax import struct

from sitewhere_tpu.errors import ErrorCode, SiteWhereError

# static buckets: one cached jit program per (bucket, batch) shape.
DEFAULT_MAX_POLICIES = 8
MAX_POLICY_BUCKET = 256        # policy slot id travels in 8 lane bits
POLICY_PARAM_SLOTS = 4         # int32 params per policy (command payload)
MAX_POLICY_LEVEL = 15          # level field travels in 4 lane bits
_I32_MIN, _I32_MAX = -(2 ** 31), 2 ** 31 - 1


class PolicySource:
    """Which alert family a policy listens to; ANY matches all four."""

    ANY = 0
    THRESHOLD = 1
    GEOFENCE = 2
    PROGRAM = 3
    MODEL = 4

    BY_NAME = {"any": ANY, "threshold": THRESHOLD, "geofence": GEOFENCE,
               "program": PROGRAM, "model": MODEL}
    NAMES = {v: k for k, v in BY_NAME.items()}


class ActuationPolicyError(SiteWhereError):
    """Invalid actuation-policy spec: names the offending field so the
    409 is actionable on REST and replicated-apply paths alike."""

    def __init__(self, message: str, field_path: str = "spec"):
        super().__init__(
            f"invalid actuation policy at {field_path}: {message}",
            ErrorCode.GENERIC, http_status=409)
        self.field_path = field_path


@struct.dataclass
class ActuationPolicyTable:
    """SoA policy columns [P] (+ params [P, 4]); replicated like the
    rule tables on sharded meshes.

    `epoch` is the per-slot generation: the actuate kernel treats a
    stored debounce record whose generation lags its policy's epoch as
    never-fired, so installing a new policy into a recycled slot resets
    debounce state lazily INSIDE the jit."""

    active: np.ndarray       # bool [P]
    tenant_idx: np.ndarray   # int32 [P], 0 = any tenant
    source: np.ndarray       # int32 [P] PolicySource
    match_slot: np.ndarray   # int32 [P], -1 = any slot of the source
    min_level: np.ndarray    # int32 [P]
    debounce_ms: np.ndarray  # int32 [P]
    command_idx: np.ndarray  # int32 [P] interned command token
    params: np.ndarray       # int32 [P, POLICY_PARAM_SLOTS]
    epoch: np.ndarray        # int32 [P] debounce-state generation

    @property
    def num_policies(self) -> int:
        return self.active.shape[0]


def empty_policy_table(max_policies: int = DEFAULT_MAX_POLICIES
                       ) -> ActuationPolicyTable:
    P = max_policies
    zp = np.zeros(P, np.int32)
    return ActuationPolicyTable(
        active=np.zeros(P, bool), tenant_idx=zp,
        source=zp.copy(), match_slot=np.full(P, -1, np.int32),
        min_level=zp.copy(), debounce_ms=zp.copy(),
        command_idx=zp.copy(),
        params=np.zeros((P, POLICY_PARAM_SLOTS), np.int32),
        epoch=zp.copy())


# ---------------------------------------------------------------------------
# spec validation / normalization (wire + store form)
# ---------------------------------------------------------------------------

def _require(cond: bool, message: str, path: str) -> None:
    if not cond:
        raise ActuationPolicyError(message, path)


def _int_in_range(value, lo: int, hi: int, message: str, path: str) -> int:
    _require(isinstance(value, int) and not isinstance(value, bool),
             message, path)
    _require(lo <= value <= hi, message, path)
    return int(value)


def policy_from_dict(data: Dict) -> Dict:
    """Validate + normalize a wire/store spec into its canonical dict.
    Raises ActuationPolicyError (409, names the field) on anything a
    compile could not turn into table rows."""
    from sitewhere_tpu.model.event import AlertLevel

    _require(isinstance(data, dict), "spec must be an object", "spec")
    token = data.get("token")
    _require(isinstance(token, str) and bool(token),
             "policy requires a string token", "spec.token")

    source = data.get("source", "any")
    _require(source in PolicySource.BY_NAME,
             f"unknown source {source!r} (one of "
             f"{sorted(PolicySource.BY_NAME)})", "spec.source")

    match_slot = data.get("match_slot", -1)
    match_slot = _int_in_range(
        match_slot, -1, _I32_MAX,
        "match_slot must be an integer >= -1 (-1 = any)",
        "spec.match_slot")
    _require(source != "any" or match_slot == -1,
             "match_slot requires a concrete source kind "
             "(slot ids are per-family)", "spec.match_slot")

    level = data.get("min_level", int(AlertLevel.WARNING))
    try:
        level = (AlertLevel[level]
                 if isinstance(level, str) and not level.lstrip("-").isdigit()
                 else AlertLevel(int(level)))
    except (KeyError, ValueError, TypeError):
        raise ActuationPolicyError(f"invalid min_level {level!r}",
                                   "spec.min_level")
    _require(0 <= int(level) <= MAX_POLICY_LEVEL,
             f"min_level must fit {MAX_POLICY_LEVEL}", "spec.min_level")

    debounce = data.get("debounce_ms", 0)
    debounce = _int_in_range(
        debounce, 0, _I32_MAX,
        "debounce_ms must be an int32 integer >= 0", "spec.debounce_ms")

    command = data.get("command")
    _require(isinstance(command, str) and bool(command),
             "policy requires a string 'command' token", "spec.command")

    params_in = data.get("params", [])
    _require(isinstance(params_in, list)
             and len(params_in) <= POLICY_PARAM_SLOTS,
             f"params must be a list of at most {POLICY_PARAM_SLOTS} "
             f"int32 values", "spec.params")
    params = [_int_in_range(v, _I32_MIN, _I32_MAX,
                            "param must be an int32 integer",
                            f"spec.params[{i}]")
              for i, v in enumerate(params_in)]

    tenant_token = data.get("tenant_token", "") or ""
    _require(isinstance(tenant_token, str),
             "'tenant_token' must be a string", "spec.tenant_token")

    return {
        "token": token,
        "tenant_token": tenant_token,
        "source": source,
        "match_slot": match_slot,
        "min_level": int(level),
        "debounce_ms": debounce,
        "command": command,
        "params": params,
        "active": bool(data.get("active", True)),
    }


# ---------------------------------------------------------------------------
# compilation: normalized spec -> table rows at one policy slot
# ---------------------------------------------------------------------------

def compile_policy_into(table: ActuationPolicyTable, slot: int, spec: Dict,
                        epoch: int, *, intern_command,
                        lookup_tenant) -> None:
    """Compile one normalized spec into policy slot `slot` of `table`.

    `intern_command` binds the command token to the engine's command
    interner (the dispatcher resolves lane rows back through its
    token_array); `lookup_tenant` scopes the policy. A tenant token that
    does not resolve deactivates the policy rather than silently
    widening to "any" — the rule every other compiler here applies."""
    spec = policy_from_dict(spec)  # idempotent; applies on every path

    command_idx = intern_command(spec["command"])
    if command_idx <= 0:
        raise ActuationPolicyError(
            f"command token {spec['command']!r} exhausted the command "
            f"interner (capacity)", "spec.command")

    active = spec["active"]
    tenant_idx = 0
    if spec["tenant_token"]:
        tenant_idx = lookup_tenant(spec["tenant_token"])
        active = active and tenant_idx > 0

    table.active[slot] = active
    table.tenant_idx[slot] = tenant_idx
    table.source[slot] = PolicySource.BY_NAME[spec["source"]]
    table.match_slot[slot] = spec["match_slot"]
    table.min_level[slot] = spec["min_level"]
    table.debounce_ms[slot] = spec["debounce_ms"]
    table.command_idx[slot] = command_idx
    table.params[slot, :] = 0
    table.params[slot, :len(spec["params"])] = np.asarray(
        spec["params"], np.int64).astype(np.int32)
    table.epoch[slot] = epoch


def dry_run_compile(spec: Dict, *, intern_command=None) -> Dict:
    """Full validation WITHOUT touching a live table: used by the REST
    create and the replicated-apply paths so a bad spec 409s before any
    store/engine mutation. Returns the normalized spec. When no command
    interner is supplied, command tokens validate structurally only —
    the engine-side compile still enforces interner capacity."""
    normalized = policy_from_dict(spec)
    table = empty_policy_table(1)
    compile_policy_into(
        table, 0, normalized, epoch=1,
        intern_command=intern_command or (lambda token: 1),
        lookup_tenant=lambda token: 1)
    return normalized
