"""Closing the loop on device: alert -> command actuation.

The reference platform's other half is command delivery back to devices
(SURVEY.md §3.4: routing -> encoding -> delivery). This package compiles
declarative per-tenant alert->command policies into fixed-shape SoA
tables the fused step evaluates right after anomaly scoring
(ops/actuate.py), fans the resulting command lane out through the
existing commands/ destinations (actuation/dispatcher.py), and refits
anomaly-model constants from accumulated feature moments when the fleet
drifts (actuation/refit.py).
"""

from sitewhere_tpu.actuation.compiler import (  # noqa: F401
    ActuationPolicyError, ActuationPolicyTable, PolicySource,
    compile_policy_into, dry_run_compile, empty_policy_table,
    policy_from_dict)
from sitewhere_tpu.actuation.store import ActuationPolicyStore  # noqa: F401
from sitewhere_tpu.actuation.dispatcher import (  # noqa: F401
    CommandFanout, deliver_via_service)
from sitewhere_tpu.actuation.refit import DriftRefitter  # noqa: F401
