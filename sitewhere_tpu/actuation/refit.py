"""Online refit under drift: keep deployed models honest as the field moves.

A model's standardization constants (per-feature mean / std) and fire
threshold are fit offline, but the fleet drifts — sensors age, seasons
turn, firmware changes the baseline. Once the constants go stale the
model either storms (every device "anomalous") or goes blind. The
actuation loop makes this urgent: a storming model now PUSHES COMMANDS.

The refitter closes the adaptation loop with data the platform already
holds on device: the fused model-state slab carries per-(device, model,
feature) EWMA accumulators and rate lanes (ops/anomaly.py), and the
device-state tensors carry every device's post-fold last measurement.
One on-demand D2H snapshot (never the hot path) yields population
moments per feature; the refit spec re-centers (mean, std) on those
moments, re-scores the observed fleet with a host-side NumPy forward
pass (bit-same equations as the oracle in tests/test_anomaly_models.py)
and re-sets the threshold at a quantile of the refit scores. The new
spec pushes through the SAME ``upsert_anomaly_model`` path every other
config change uses — so it rides `_model` gossip to every peer, and the
slot's epoch bump resets feature state lazily inside the jit.

``time-to-adapt`` (bench.py drift scenario) is the end-to-end measure:
inject a mean shift, watch the stale model storm, refit, and report the
wall time until the fire rate returns to baseline.
"""

from __future__ import annotations

import copy
import logging
from typing import Dict, List, Optional

import numpy as np

LOGGER = logging.getLogger("sitewhere.actuation")

DEFAULT_THRESHOLD_QUANTILE = 0.99
# the refit threshold is margin * quantile(refit scores): the snapshot is
# one frozen instant per device, so its top quantile underestimates the
# step-to-step score spread — fresh draws would trip a bare quantile
DEFAULT_THRESHOLD_MARGIN = 3.0
MIN_REFIT_DEVICES = 4
MIN_REFIT_STD = 1e-3


def forward_scores(spec: Dict, feats: np.ndarray) -> np.ndarray:
    """Host-side NumPy forward pass over RAW feature rows [N, F] using
    the spec's (mean, std) and weights — the oracle equations from
    ops/anomaly.py: tanh hidden layers; mlp score = sigmoid(out_w . h +
    out_b); autoencoder final layer LINEAR, score = mean squared
    reconstruction error of the normalized features."""
    feats = np.asarray(feats, np.float32)
    mean = np.array([f.get("mean", 0.0) for f in spec["features"]],
                    np.float32)
    std = np.array([f.get("std", 1.0) for f in spec["features"]],
                   np.float32)
    z = (feats - mean) / std
    h = z
    layers = spec.get("layers", [])
    last = len(layers) - 1
    for li, layer in enumerate(layers):
        W = np.asarray(layer["weights"], np.float32)
        b = np.asarray(layer["bias"], np.float32)
        h = h @ W.T + b
        if not (spec["kind"] == "autoencoder" and li == last):
            h = np.tanh(h)
    if spec["kind"] == "autoencoder":
        return ((h - z) ** 2).mean(axis=1)
    out = spec["output"]
    logit = h @ np.asarray(out["weights"], np.float32) + out["bias"]
    return 1.0 / (1.0 + np.exp(-logit))


class DriftRefitter:
    """Snapshot live feature state for one model and refit its
    standardization constants and threshold against the CURRENT fleet.

    Works against either engine: sharded model/device state arrives with
    a leading shard axis and flattens device-major — moments are
    permutation-invariant, so the shard interleave does not matter."""

    def __init__(self, engine, *,
                 min_devices: int = MIN_REFIT_DEVICES,
                 min_std: float = MIN_REFIT_STD,
                 threshold_quantile: float = DEFAULT_THRESHOLD_QUANTILE,
                 threshold_margin: float = DEFAULT_THRESHOLD_MARGIN):
        self.engine = engine
        self.min_devices = int(min_devices)
        self.min_std = float(min_std)
        self.threshold_quantile = float(threshold_quantile)
        self.threshold_margin = float(threshold_margin)
        self.refits = 0

    # -- state snapshot ----------------------------------------------------

    def _model_entry(self, token: str) -> Dict:
        for entry in self.engine.anomaly_model_manifest():
            if entry["spec"]["token"] == token:
                return entry
        raise KeyError(f"unknown anomaly model '{token}'")

    def feature_matrix(self, token: str) -> np.ndarray:
        """Per-device RAW feature rows [N, F] for every device that has
        observed ALL of the model's features (NaN-free, generation
        current); N == 0 when nothing qualified yet.

        Feature sources mirror what the kernel reads: `value` features
        read the post-fold last measurement (device state), `ewma` the
        accumulator lane, `rate` the last computed rate lane (model
        state slab)."""
        from sitewhere_tpu.ops.slab import unpack_state_slab_np

        entry = self._model_entry(token)
        slot, epoch, spec = entry["slot"], entry["epoch"], entry["spec"]
        eng = self.engine
        with eng._state_lock:
            slab = np.asarray(eng._model_state.slab)
            last_mm = np.asarray(eng._state.last_measurement)
            last_mm_ts = np.asarray(eng._state.last_measurement_ts)
        if slab.ndim == 4:            # sharded [S, D/S, P, L] -> [D, P, L]
            slab = slab.reshape((-1,) + slab.shape[2:])
            last_mm = last_mm.reshape((-1,) + last_mm.shape[2:])
            last_mm_ts = last_mm_ts.reshape((-1,) + last_mm_ts.shape[2:])
        planes = unpack_state_slab_np(slab)
        D = slab.shape[0]
        _NEG = -(2 ** 31)
        cols: List[np.ndarray] = []
        ok = planes["row_gen"][:, slot] == epoch
        for i, feature in enumerate(spec["features"]):
            kind = feature["feature"]
            if kind == "value":
                mm = eng.packer.measurements.lookup(feature["measurement"])
                col = last_mm[:, mm].astype(np.float32)
                seen = last_mm_ts[:, mm] != _NEG
            elif kind == "ewma":
                col = planes["value"][:, slot, i]
                seen = planes["counter"][:, slot, i] >= 1
            else:                      # rate
                col = planes["aux"][:, slot, i]
                seen = planes["counter"][:, slot, i] >= 2
            cols.append(col)
            ok = ok & seen & np.isfinite(col)
        if not cols:
            return np.empty((0, 0), np.float32)
        feats = np.stack(cols, axis=1)[ok]
        return np.asarray(feats, np.float32).reshape(int(ok.sum()),
                                                     len(cols))

    def snapshot_moments(self, token: str) -> List[Dict]:
        """Per-feature population moments over the qualified fleet."""
        entry = self._model_entry(token)
        feats = self.feature_matrix(token)
        out = []
        for i, feature in enumerate(entry["spec"]["features"]):
            if feats.shape[0]:
                col = feats[:, i]
                out.append({"feature": feature["feature"],
                            "measurement": feature["measurement"],
                            "n": int(feats.shape[0]),
                            "mean": float(col.mean()),
                            "std": float(col.std())})
            else:
                out.append({"feature": feature["feature"],
                            "measurement": feature["measurement"],
                            "n": 0, "mean": 0.0, "std": 0.0})
        return out

    # -- refit -------------------------------------------------------------

    def refit(self, token: str, *, apply: bool = True,
              refit_threshold: bool = True) -> Optional[Dict]:
        """Re-center the model's feature constants on the live fleet and
        (optionally) re-set its threshold at `threshold_quantile` of the
        refit scores. Returns the report dict, or None when fewer than
        `min_devices` devices qualify (refusing a refit on thin data is
        the safe failure — the stale model keeps running)."""
        entry = self._model_entry(token)
        spec = copy.deepcopy(entry["spec"])
        feats = self.feature_matrix(token)
        n = int(feats.shape[0])
        if n < self.min_devices:
            LOGGER.warning(
                "refit of '%s' skipped: %d qualified devices < %d",
                token, n, self.min_devices)
            return None
        for i, feature in enumerate(spec["features"]):
            col = feats[:, i]
            feature["mean"] = float(col.mean())
            feature["std"] = float(max(col.std(), self.min_std))
        old_threshold = spec["threshold"]
        if refit_threshold:
            scores = forward_scores(spec, feats)
            q = float(np.quantile(scores, self.threshold_quantile))
            spec["threshold"] = max(q * self.threshold_margin,
                                    float(np.finfo(np.float32).tiny))
        report = {"token": token, "devices": n,
                  "old_threshold": float(old_threshold),
                  "threshold": float(spec["threshold"]),
                  "features": [{"measurement": f["measurement"],
                                "mean": f["mean"], "std": f["std"]}
                               for f in spec["features"]],
                  "applied": bool(apply)}
        if apply:
            # the ONE write path: epoch bumps (state resets lazily in
            # the jit) and instance-level wiring replicates via gossip
            self.engine.upsert_anomaly_model(spec)
            self.refits += 1
            LOGGER.info(
                "refit '%s': threshold %.4f -> %.4f over %d devices",
                token, report["old_threshold"], report["threshold"], n)
        return report


class DriftRefitJobExecutor:
    """ScheduleManager executor (ScheduledJobType.DRIFT_REFIT): one job
    fire = one unattended refit sweep.

    PR 19's named follow-up — refits ran only when an operator POSTed
    them. Registered on every tenant engine's schedule manager
    (multitenant/engine.py), so a simple-trigger schedule turns the
    adaptation loop autonomous: each fire walks the engine's installed
    anomaly models (or the comma-separated ``models`` subset in the job
    configuration) and pushes a refit through the same gossip-replicated
    ``upsert_anomaly_model`` path the manual route uses. Thin-data
    models are skipped by the refitter itself (`min_devices`), so an
    unattended sweep can never clobber a model with a bad fit. Sweeps
    are counted under ``actuation.refit_sweeps``; instance wiring is
    opt-in via the off-by-default ``actuation.refit_interval_s`` knob
    (runtime/config.py)."""

    # job_configuration key: comma-separated model tokens ("" = all)
    MODELS_KEY = "models"

    def __init__(self, refitter: DriftRefitter, metrics=None):
        from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
        self.refitter = refitter
        m = metrics or GLOBAL_METRICS
        self.sweep_counter = m.counter("actuation.refit_sweeps")

    def execute(self, job) -> Dict:
        cfg = getattr(job, "job_configuration", None) or {}
        wanted = [t for t in
                  (cfg.get(self.MODELS_KEY) or "").split(",") if t]
        if not wanted:
            wanted = [entry["spec"]["token"] for entry in
                      self.refitter.engine.anomaly_model_manifest()]
        applied = 0
        for token in wanted:
            try:
                report = self.refitter.refit(token, apply=True)
            except Exception:
                LOGGER.exception("scheduled refit of '%s' failed", token)
                continue
            if report is not None:
                applied += 1
        self.sweep_counter.inc()
        return {"models": len(wanted), "applied": applied}
