"""Delivery fan-out: lane-resolved command fires -> destinations.

Closes the sense->decide->act loop off device: the engine's materialize
pass resolves the step's command lane into fire records
(pipeline/engine.py `_materialize_commands`) and hands them here in the
SAME pass, so the `detection_to_actuation` age edge the flight recorder
closes after fan-out measures real delivery work — not a queue handoff.

Delivery discipline mirrors the bus consumers (commands/delivery.py):
bounded in-line retries per fire (the `command_delivery_error` fault
point arms each attempt), then the fire parks on the bounded dead-letter
list instead of blocking the step loop. Conservation is the drill-tested
invariant: ``delivered + parked + suppressed == fires handed in`` —
nothing is silently lost (tests/test_actuation.py).

Exactly-once across failover rides the replay barrier
(runtime/recovery.py): while a restored engine replays inbound rows that
were already durable before the checkpoint, the replayed steps re-fire
their policies bit-identically — rebuilding the debounce state — but the
re-resolved fires are suppressed here instead of re-delivered.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.runtime.bus import jittered
from sitewhere_tpu.runtime.faults import fault_point
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
from sitewhere_tpu.runtime.recovery import GLOBAL_REPLAY_BARRIER

LOGGER = logging.getLogger("sitewhere.actuation")

DEFAULT_DELIVERY_RETRIES = 2
DEFAULT_MAX_PARKED = 1024


class CommandFanout:
    """Bounded-retry fan-out for actuation command fires.

    `deliver` is the transport: a callable taking one fire dict and
    raising on failure. The default is the in-memory sink (`self.sent`)
    used by tests and the bench; `deliver_via_service` adapts the full
    tenant command-delivery stack (resolve + route + encode).
    Attach an instance as ``engine.command_dispatcher`` — the engine
    calls ``dispatch(engine, fires)`` from its materialize pass.
    """

    def __init__(self, deliver: Optional[Callable[[Dict], None]] = None,
                 *, max_retries: int = DEFAULT_DELIVERY_RETRIES,
                 max_parked: int = DEFAULT_MAX_PARKED,
                 metrics=GLOBAL_METRICS, barrier=GLOBAL_REPLAY_BARRIER):
        self.deliver = deliver if deliver is not None else self._sink
        self.max_retries = int(max_retries)
        self.max_parked = int(max_parked)
        self.sent: List[Dict] = []        # default in-memory sink
        self.parked: List[Dict] = []      # dead-letter list (bounded)
        self.delivered_count = 0
        self.parked_count = 0
        self.suppressed_count = 0
        self.parked_overflow = 0
        self.retry_count = 0
        self.barrier = barrier
        self._delivered = metrics.counter("commands.delivered")
        self._parked = metrics.counter("commands.parked")
        self._suppressed = metrics.counter("commands.suppressed")

    # -- engine-facing protocol -------------------------------------------

    def dispatch(self, engine, fires: List[Dict]) -> None:
        for fire in fires:
            if (self.barrier is not None
                    and self.barrier.active(fire.get("tenant") or None)):
                # replayed step: the command already went out before the
                # checkpoint this engine restored from
                self.suppressed_count += 1
                self._suppressed.inc()
                continue
            self._deliver_one(fire)

    # -- delivery ----------------------------------------------------------

    def _deliver_one(self, fire: Dict) -> None:
        attempt = 0
        while True:
            try:
                fault_point("command_delivery_error")
                self.deliver(fire)
                self.delivered_count += 1
                self._delivered.inc()
                return
            except Exception as exc:
                attempt += 1
                if attempt > self.max_retries:
                    self._park(fire, exc)
                    return
                self.retry_count += 1
                time.sleep(jittered(0.005 * (2 ** (attempt - 1))))

    def _park(self, fire: Dict, exc: Exception) -> None:
        self.parked_count += 1
        self._parked.inc()
        LOGGER.warning(
            "command fire parked after %d attempts: policy=%s device=%s "
            "command=%s (%s); parked=%d total",
            self.max_retries + 1, fire.get("policy"), fire.get("device"),
            fire.get("command"), exc, self.parked_count)
        if len(self.parked) < self.max_parked:
            self.parked.append(dict(fire, error=str(exc)))
        else:
            # counts stay exact (parked_count above) even when the
            # dead-letter LIST is full — the overflow is loud, not silent
            self.parked_overflow += 1
            LOGGER.error(
                "dead-letter list full (%d); parked fire record dropped "
                "(parked_overflow=%d)", self.max_parked,
                self.parked_overflow)

    def _sink(self, fire: Dict) -> None:
        self.sent.append(fire)

    # -- dead-letter drain -------------------------------------------------

    def redeliver_parked(self) -> int:
        """One redelivery sweep over the dead-letter list (operator- or
        scheduler-driven). Fires that fail again re-park; returns how
        many went out."""
        parked, self.parked = self.parked, []
        ok = 0
        for fire in parked:
            fire = {k: v for k, v in fire.items() if k != "error"}
            before = self.parked_count
            self._deliver_one(fire)
            if self.parked_count == before:
                ok += 1
        return ok

    def stats(self) -> Dict[str, int]:
        return {"delivered": self.delivered_count,
                "parked": self.parked_count,
                "suppressed": self.suppressed_count,
                "retries": self.retry_count,
                "parked_overflow": self.parked_overflow,
                "dead_letter_depth": len(self.parked)}


def deliver_via_service(service) -> Callable[[Dict], None]:
    """Adapt the tenant command-delivery stack (commands/delivery.py) as
    a CommandFanout transport: fire -> DeviceCommandInvocation against
    the device's ACTIVE assignment -> resolve / route / encode / deliver.
    Raises (-> bounded retry, then dead-letter) when the device has no
    active assignment or the command token is unknown to the registry."""
    from sitewhere_tpu.errors import SiteWhereError
    from sitewhere_tpu.model.event import (
        CommandInitiator, DeviceCommandInvocation)

    def deliver(fire: Dict) -> None:
        device = service.registry.get_device_by_token(fire["device"])
        if device is None:
            raise SiteWhereError(f"unknown device '{fire['device']}'")
        assignment = service.registry.get_active_assignment(device.id)
        if assignment is None:
            raise SiteWhereError(
                f"device '{fire['device']}' has no active assignment")
        params = {f"p{i}": str(v)
                  for i, v in enumerate(fire.get("params", []))}
        service.deliver(DeviceCommandInvocation(
            device_id=device.id,
            initiator=CommandInitiator.SCRIPT,
            initiator_id=f"actuation:{fire['policy']}",
            target_id=assignment.token,
            command_token=fire["command"],
            parameter_values=params))

    return deliver
