"""QR code encoder: byte mode, versions 1-10, EC levels L/M/Q/H.

A complete, dependency-free implementation of ISO/IEC 18004 encoding —
Reed-Solomon ECC over GF(256), block interleaving, the eight data masks with
penalty-scored selection, format/version BCH codes — producing a boolean
module matrix. Replaces the reference's ZXing dependency
(service-label-generation/src/main/java/com/sitewhere/labels/symbology/
QrCodeGenerator.java, which delegates to QRCode.from(uri)); the entity-URI
payloads that service encodes (sitewhere://device/<token>, ~20-80 bytes) fit
comfortably in versions 1-10 (v10-L holds 271 bytes).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

# -- GF(256) arithmetic (polynomial 0x11d) -----------------------------------

_EXP = np.zeros(512, np.int32)
_LOG = np.zeros(256, np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= 0x11d
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def _gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[_LOG[a] + _LOG[b]])


def _rs_generator(n_ec: int) -> List[int]:
    """Generator polynomial coefficients (monic, ascending degree order of
    the remainder algorithm: g[0] is the x^{n_ec-1} coefficient side)."""
    gen = [1]
    for i in range(n_ec):
        nxt = [0] * (len(gen) + 1)
        for j, c in enumerate(gen):
            nxt[j] ^= _gf_mul(c, _EXP[i])
            nxt[j + 1] ^= c
        gen = nxt
    return gen[::-1]  # highest degree first


def rs_ecc(data: Sequence[int], n_ec: int) -> List[int]:
    """Reed-Solomon error-correction codewords for a data block."""
    gen = _rs_generator(n_ec)
    rem = list(data) + [0] * n_ec
    for i in range(len(data)):
        factor = rem[i]
        if factor:
            for j in range(1, len(gen)):
                rem[i + j] ^= _gf_mul(gen[j], factor)
    return rem[len(data):]


# -- capacity tables, versions 1-10 ------------------------------------------
# (ec_per_block, blocks1, data1, blocks2, data2) per level
_EC_TABLE = {
    1: {"L": (7, 1, 19, 0, 0), "M": (10, 1, 16, 0, 0),
        "Q": (13, 1, 13, 0, 0), "H": (17, 1, 9, 0, 0)},
    2: {"L": (10, 1, 34, 0, 0), "M": (16, 1, 28, 0, 0),
        "Q": (22, 1, 22, 0, 0), "H": (28, 1, 16, 0, 0)},
    3: {"L": (15, 1, 55, 0, 0), "M": (26, 1, 44, 0, 0),
        "Q": (18, 2, 17, 0, 0), "H": (22, 2, 13, 0, 0)},
    4: {"L": (20, 1, 80, 0, 0), "M": (18, 2, 32, 0, 0),
        "Q": (26, 2, 24, 0, 0), "H": (16, 4, 9, 0, 0)},
    5: {"L": (26, 1, 108, 0, 0), "M": (24, 2, 43, 0, 0),
        "Q": (18, 2, 15, 2, 16), "H": (22, 2, 11, 2, 12)},
    6: {"L": (18, 2, 68, 0, 0), "M": (16, 4, 27, 0, 0),
        "Q": (24, 4, 19, 0, 0), "H": (28, 4, 15, 0, 0)},
    7: {"L": (20, 2, 78, 0, 0), "M": (18, 4, 31, 0, 0),
        "Q": (18, 2, 14, 4, 15), "H": (26, 4, 13, 1, 14)},
    8: {"L": (24, 2, 97, 0, 0), "M": (22, 2, 38, 2, 39),
        "Q": (22, 4, 18, 2, 19), "H": (26, 4, 14, 2, 15)},
    9: {"L": (30, 2, 116, 0, 0), "M": (22, 3, 36, 2, 37),
        "Q": (20, 4, 16, 4, 17), "H": (24, 4, 12, 4, 13)},
    10: {"L": (18, 2, 68, 2, 69), "M": (26, 4, 43, 1, 44),
         "Q": (24, 6, 19, 2, 20), "H": (28, 6, 15, 2, 16)},
}

_ALIGNMENT = {
    1: [], 2: [6, 18], 3: [6, 22], 4: [6, 26], 5: [6, 30], 6: [6, 34],
    7: [6, 22, 38], 8: [6, 24, 42], 9: [6, 26, 46], 10: [6, 28, 50],
}

_EC_BITS = {"L": 0b01, "M": 0b00, "Q": 0b11, "H": 0b10}


def data_capacity(version: int, level: str) -> int:
    """Max byte-mode payload bytes for a (version, level)."""
    ec, b1, d1, b2, d2 = _EC_TABLE[version][level]
    total_data = b1 * d1 + b2 * d2
    # mode (4 bits) + char count (8 bits for v<=9, 16 for v10)
    overhead_bits = 4 + (16 if version >= 10 else 8)
    return total_data - (overhead_bits + 7) // 8


def pick_version(n_bytes: int, level: str) -> int:
    for v in range(1, 11):
        if data_capacity(v, level) >= n_bytes:
            return v
    raise ValueError(f"payload of {n_bytes} bytes exceeds version-10-{level} "
                     f"capacity ({data_capacity(10, level)})")


# -- bit stream + codewords ---------------------------------------------------

def _encode_codewords(payload: bytes, version: int, level: str) -> List[int]:
    ec, b1, d1, b2, d2 = _EC_TABLE[version][level]
    n_data = b1 * d1 + b2 * d2
    bits: List[int] = []

    def put(value: int, n: int):
        for i in range(n - 1, -1, -1):
            bits.append((value >> i) & 1)

    put(0b0100, 4)  # byte mode
    put(len(payload), 16 if version >= 10 else 8)
    for byte in payload:
        put(byte, 8)
    # terminator (up to 4 zero bits), pad to byte boundary
    free = n_data * 8 - len(bits)
    put(0, min(4, free))
    if len(bits) % 8:
        put(0, 8 - len(bits) % 8)
    codewords = [int("".join(map(str, bits[i:i + 8])), 2)
                 for i in range(0, len(bits), 8)]
    pad = [0xEC, 0x11]
    i = 0
    while len(codewords) < n_data:
        codewords.append(pad[i % 2])
        i += 1
    return codewords


def _interleave(codewords: List[int], version: int, level: str) -> List[int]:
    ec, b1, d1, b2, d2 = _EC_TABLE[version][level]
    blocks: List[List[int]] = []
    pos = 0
    for _ in range(b1):
        blocks.append(codewords[pos:pos + d1])
        pos += d1
    for _ in range(b2):
        blocks.append(codewords[pos:pos + d2])
        pos += d2
    eccs = [rs_ecc(blk, ec) for blk in blocks]
    out: List[int] = []
    for i in range(max(d1, d2)):
        for blk in blocks:
            if i < len(blk):
                out.append(blk[i])
    for i in range(ec):
        for e in eccs:
            out.append(e[i])
    return out


# -- matrix construction ------------------------------------------------------

def _function_modules(version: int) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (matrix with function patterns placed, reserved mask)."""
    size = 17 + 4 * version
    m = np.zeros((size, size), bool)
    reserved = np.zeros((size, size), bool)

    def finder(r, c):
        for dr in range(-1, 8):
            for dc in range(-1, 8):
                rr, cc = r + dr, c + dc
                if not (0 <= rr < size and 0 <= cc < size):
                    continue
                inside = 0 <= dr <= 6 and 0 <= dc <= 6
                dark = inside and (dr in (0, 6) or dc in (0, 6)
                                   or (2 <= dr <= 4 and 2 <= dc <= 4))
                m[rr, cc] = dark
                reserved[rr, cc] = True

    finder(0, 0)
    finder(0, size - 7)
    finder(size - 7, 0)
    # timing patterns
    for i in range(8, size - 8):
        m[6, i] = m[i, 6] = (i % 2 == 0)
        reserved[6, i] = reserved[i, 6] = True
    # alignment patterns
    centers = _ALIGNMENT[version]
    for r in centers:
        for c in centers:
            if (r < 9 and c < 9) or (r < 9 and c > size - 10) \
                    or (r > size - 10 and c < 9):
                continue  # overlaps a finder
            for dr in range(-2, 3):
                for dc in range(-2, 3):
                    m[r + dr, c + dc] = (max(abs(dr), abs(dc)) != 1)
                    reserved[r + dr, c + dc] = True
    # format info areas
    for i in range(9):
        reserved[8, i] = reserved[i, 8] = True
    for i in range(8):
        reserved[8, size - 1 - i] = reserved[size - 1 - i, 8] = True
    m[size - 8, 8] = True  # dark module
    reserved[size - 8, 8] = True
    # version info (v >= 7)
    if version >= 7:
        reserved[size - 11:size - 8, 0:6] = True
        reserved[0:6, size - 11:size - 8] = True
    return m, reserved


def _place_data(m: np.ndarray, reserved: np.ndarray,
                codewords: List[int]) -> List[Tuple[int, int]]:
    """Zigzag placement; returns the (row, col) of each data bit in order."""
    size = m.shape[0]
    bits = [(cw >> (7 - i)) & 1 for cw in codewords for i in range(8)]
    coords: List[Tuple[int, int]] = []
    bit_i = 0
    col = size - 1
    upward = True
    while col > 0:
        if col == 6:  # skip the vertical timing column entirely
            col -= 1
        rows = range(size - 1, -1, -1) if upward else range(size)
        for row in rows:
            for c in (col, col - 1):
                if reserved[row, c]:
                    continue
                if bit_i < len(bits):
                    m[row, c] = bool(bits[bit_i])
                coords.append((row, c))
                bit_i += 1
        upward = not upward
        col -= 2
    return coords


_MASKS = [
    lambda r, c: (r + c) % 2 == 0,
    lambda r, c: r % 2 == 0,
    lambda r, c: c % 3 == 0,
    lambda r, c: (r + c) % 3 == 0,
    lambda r, c: (r // 2 + c // 3) % 2 == 0,
    lambda r, c: (r * c) % 2 + (r * c) % 3 == 0,
    lambda r, c: ((r * c) % 2 + (r * c) % 3) % 2 == 0,
    lambda r, c: ((r + c) % 2 + (r * c) % 3) % 2 == 0,
]


def _penalty(m: np.ndarray) -> int:
    size = m.shape[0]
    score = 0
    # N1: runs of >= 5 same-color modules
    for grid in (m, m.T):
        for row in grid:
            run = 1
            for i in range(1, size):
                if row[i] == row[i - 1]:
                    run += 1
                else:
                    if run >= 5:
                        score += 3 + run - 5
                    run = 1
            if run >= 5:
                score += 3 + run - 5
    # N2: 2x2 blocks
    blocks = (m[:-1, :-1] == m[1:, :-1]) & (m[:-1, :-1] == m[:-1, 1:]) \
        & (m[:-1, :-1] == m[1:, 1:])
    score += 3 * int(blocks.sum())
    # N3: finder-like 1011101 pattern with 4 light modules on either side
    pat1 = np.array([1, 0, 1, 1, 1, 0, 1, 0, 0, 0, 0], bool)
    pat2 = pat1[::-1]
    for grid in (m, m.T):
        for row in grid:
            for i in range(size - 10):
                win = row[i:i + 11]
                if np.array_equal(win, pat1) or np.array_equal(win, pat2):
                    score += 40
    # N4: dark-module balance
    dark_pct = m.sum() * 100.0 / (size * size)
    score += 10 * int(abs(dark_pct - 50) // 5)
    return score


def _bch_format(level: str, mask: int) -> int:
    data = (_EC_BITS[level] << 3) | mask
    rem = data << 10
    gen = 0b10100110111
    for i in range(14, 9, -1):
        if rem & (1 << i):
            rem ^= gen << (i - 10)
    return ((data << 10) | rem) ^ 0b101010000010010


def _bch_version(version: int) -> int:
    rem = version << 12
    gen = 0b1111100100101
    for i in range(17, 11, -1):
        if rem & (1 << i):
            rem ^= gen << (i - 12)
    return (version << 12) | rem


def _write_format(m: np.ndarray, level: str, mask: int) -> None:
    size = m.shape[0]
    fmt = _bch_format(level, mask)
    bits = [(fmt >> i) & 1 for i in range(14, -1, -1)]  # bit14 first
    # around the top-left finder
    pos_a = [(8, 0), (8, 1), (8, 2), (8, 3), (8, 4), (8, 5), (8, 7), (8, 8),
             (7, 8), (5, 8), (4, 8), (3, 8), (2, 8), (1, 8), (0, 8)]
    # split between bottom-left and top-right
    pos_b = [(size - 1, 8), (size - 2, 8), (size - 3, 8), (size - 4, 8),
             (size - 5, 8), (size - 6, 8), (size - 7, 8),
             (8, size - 8), (8, size - 7), (8, size - 6), (8, size - 5),
             (8, size - 4), (8, size - 3), (8, size - 2), (8, size - 1)]
    for (r, c), b in zip(pos_a, bits):
        m[r, c] = bool(b)
    for (r, c), b in zip(pos_b, bits):
        m[r, c] = bool(b)


def _write_version(m: np.ndarray, version: int) -> None:
    if version < 7:
        return
    size = m.shape[0]
    v = _bch_version(version)
    for i in range(18):
        bit = bool((v >> i) & 1)
        m[size - 11 + i % 3, i // 3] = bit
        m[i // 3, size - 11 + i % 3] = bit


def encode_qr(payload: bytes, level: str = "M",
              version: Optional[int] = None,
              mask: Optional[int] = None) -> np.ndarray:
    """Encode bytes into a QR module matrix (True = dark). The mask is chosen
    by the standard's four penalty rules unless forced via `mask` (0-7)."""
    if isinstance(payload, str):
        payload = payload.encode()
    if level not in _EC_BITS:
        raise ValueError(f"EC level {level!r}: expected one of L, M, Q, H")
    if version is None:
        version = pick_version(len(payload), level)
    elif not 1 <= version <= 10:
        raise ValueError("version must be in 1..10")
    elif data_capacity(version, level) < len(payload):
        raise ValueError(f"payload too large for version {version}-{level}")
    if mask is not None and not 0 <= mask <= 7:
        raise ValueError("mask must be in 0..7")
    codewords = _interleave(_encode_codewords(payload, version, level),
                            version, level)
    base, reserved = _function_modules(version)
    coords = _place_data(base, reserved, codewords)

    best: Optional[np.ndarray] = None
    best_score = None
    candidates = range(8) if mask is None else [mask]
    for mask_id in candidates:
        mask_fn = _MASKS[mask_id]
        m = base.copy()
        for (r, c) in coords:
            if mask_fn(r, c):
                m[r, c] = not m[r, c]
        _write_format(m, level, mask_id)
        _write_version(m, version)
        score = _penalty(m)
        if best_score is None or score < best_score:
            best, best_score = m, score
    return best


def qr_matrix_to_image(matrix: np.ndarray, scale: int = 8,
                       border: int = 4) -> np.ndarray:
    """Module matrix -> uint8 grayscale image (0=dark, 255=light) with the
    standard quiet zone."""
    size = matrix.shape[0]
    img = np.full(((size + 2 * border) * scale, (size + 2 * border) * scale),
                  255, np.uint8)
    modules = np.where(matrix, 0, 255).astype(np.uint8)
    scaled = np.kron(modules, np.ones((scale, scale), np.uint8))
    off = border * scale
    img[off:off + size * scale, off:off + size * scale] = scaled
    return img
