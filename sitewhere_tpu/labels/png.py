"""Minimal PNG writer (grayscale 8-bit), zlib + struct only.

The label service returns image bytes over REST like the reference's
QrCodeGenerator (QRCode.to(ImageType.PNG).stream()); no imaging dependency
is needed for lossless grayscale output.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

_SIG = b"\x89PNG\r\n\x1a\n"


def _chunk(tag: bytes, body: bytes) -> bytes:
    return (struct.pack(">I", len(body)) + tag + body
            + struct.pack(">I", zlib.crc32(tag + body) & 0xFFFFFFFF))


def write_png_gray(img: np.ndarray) -> bytes:
    """uint8 [H, W] grayscale -> PNG bytes."""
    if img.dtype != np.uint8 or img.ndim != 2:
        raise ValueError("expected uint8 [H, W] grayscale image")
    h, w = img.shape
    ihdr = struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0)  # 8-bit gray
    # filter byte 0 per scanline
    raw = b"".join(b"\x00" + img[r].tobytes() for r in range(h))
    return (_SIG + _chunk(b"IHDR", ihdr)
            + _chunk(b"IDAT", zlib.compress(raw, 6))
            + _chunk(b"IEND", b""))


def read_png_gray(data: bytes) -> np.ndarray:
    """Inverse of write_png_gray for round-trip tests (only the subset this
    module writes: 8-bit grayscale, filter 0)."""
    if not data.startswith(_SIG):
        raise ValueError("not a PNG")
    pos = len(_SIG)
    w = h = None
    idat = b""
    while pos < len(data):
        (length,) = struct.unpack_from(">I", data, pos)
        tag = data[pos + 4:pos + 8]
        body = data[pos + 8:pos + 8 + length]
        if tag == b"IHDR":
            w, h, depth, ctype = struct.unpack_from(">IIBB", body)
            if depth != 8 or ctype != 0:
                raise ValueError("unsupported PNG subset")
        elif tag == b"IDAT":
            idat += body
        pos += 12 + length
    raw = zlib.decompress(idat)
    out = np.zeros((h, w), np.uint8)
    stride = w + 1
    for r in range(h):
        line = raw[r * stride:(r + 1) * stride]
        if line[0] != 0:
            raise ValueError("unsupported PNG filter")
        out[r] = np.frombuffer(line[1:], np.uint8)
    return out
