"""Label generation (QR symbology) — service-label-generation rebuilt."""

from sitewhere_tpu.labels.manager import (
    EntityUriProvider, LabelGeneratorManager, QrCodeGenerator,
    SITEWHERE_PROTOCOL)
from sitewhere_tpu.labels.png import read_png_gray, write_png_gray
from sitewhere_tpu.labels.qr import (
    data_capacity, encode_qr, pick_version, qr_matrix_to_image, rs_ecc)

__all__ = [
    "EntityUriProvider", "LabelGeneratorManager", "QrCodeGenerator",
    "SITEWHERE_PROTOCOL", "read_png_gray", "write_png_gray",
    "data_capacity", "encode_qr", "pick_version", "qr_matrix_to_image",
    "rs_ecc",
]
