"""Label generation service: entity URIs -> symbology images.

Reference: service-label-generation —
  DefaultEntityUriProvider.java (sitewhere://<type>/<token> URIs),
  QrCodeGenerator.java (per-generator image config),
  LabelGeneratorManager.java (named generator registry, getLabelGenerator),
  grpc/LabelGenerationImpl.java (get*Label rpcs per entity type).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.labels.png import write_png_gray
from sitewhere_tpu.labels.qr import encode_qr, qr_matrix_to_image
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

SITEWHERE_PROTOCOL = "sitewhere://"


class EntityUriProvider:
    """sitewhere:// URIs for every addressable entity type
    (DefaultEntityUriProvider.java)."""

    @staticmethod
    def uri(entity_type: str, token: str) -> str:
        return f"{SITEWHERE_PROTOCOL}{entity_type}/{token}"

    customer_type = staticmethod(lambda t: EntityUriProvider.uri("customertype", t))
    customer = staticmethod(lambda t: EntityUriProvider.uri("customer", t))
    area_type = staticmethod(lambda t: EntityUriProvider.uri("areatype", t))
    area = staticmethod(lambda t: EntityUriProvider.uri("area", t))
    device_type = staticmethod(lambda t: EntityUriProvider.uri("devicetype", t))
    device = staticmethod(lambda t: EntityUriProvider.uri("device", t))
    device_group = staticmethod(lambda t: EntityUriProvider.uri("devicegroup", t))
    assignment = staticmethod(lambda t: EntityUriProvider.uri("assignment", t))
    asset_type = staticmethod(lambda t: EntityUriProvider.uri("assettype", t))
    asset = staticmethod(lambda t: EntityUriProvider.uri("asset", t))


class QrCodeGenerator(LifecycleComponent):
    """QR symbology generator (QrCodeGenerator.java): configurable module
    scale, quiet zone, and EC level; produces PNG bytes."""

    def __init__(self, generator_id: str = "qrcode", name: str = "QR-Code",
                 scale: int = 8, border: int = 4, ec_level: str = "M"):
        super().__init__(f"label-generator:{generator_id}")
        self.id = generator_id
        self.generator_name = name
        self.scale = scale
        self.border = border
        self.ec_level = ec_level

    def generate(self, uri: str) -> bytes:
        matrix = encode_qr(uri.encode(), level=self.ec_level)
        return write_png_gray(qr_matrix_to_image(matrix, self.scale,
                                                 self.border))


class LabelGeneratorManager(LifecycleComponent):
    """Named registry of label generators (LabelGeneratorManager.java:
    getLabelGenerators/getLabelGenerator)."""

    def __init__(self, generators: Optional[List] = None):
        super().__init__("label-generator-manager")
        gens = generators if generators is not None else [QrCodeGenerator()]
        self._generators: Dict[str, object] = {}
        for g in gens:
            self._generators[g.id] = g
            self.add_nested(g)

    def generator_ids(self) -> List[str]:
        return list(self._generators)

    def get_generator(self, generator_id: str):
        gen = self._generators.get(generator_id)
        if gen is None:
            raise SiteWhereError(
                f"label generator '{generator_id}' not found",
                ErrorCode.GENERIC, http_status=404)
        return gen

    # -- entity label entry points (LabelGenerationImpl rpcs) ---------------

    def label_for(self, generator_id: str, entity_type: str,
                  token: str) -> bytes:
        uri = EntityUriProvider.uri(entity_type, token)
        return self.get_generator(generator_id).generate(uri)

    def device_label(self, generator_id: str, token: str) -> bytes:
        return self.label_for(generator_id, "device", token)

    def device_type_label(self, generator_id: str, token: str) -> bytes:
        return self.label_for(generator_id, "devicetype", token)

    def assignment_label(self, generator_id: str, token: str) -> bytes:
        return self.label_for(generator_id, "assignment", token)

    def area_label(self, generator_id: str, token: str) -> bytes:
        return self.label_for(generator_id, "area", token)

    def customer_label(self, generator_id: str, token: str) -> bytes:
        return self.label_for(generator_id, "customer", token)

    def asset_label(self, generator_id: str, token: str) -> bytes:
        return self.label_for(generator_id, "asset", token)
