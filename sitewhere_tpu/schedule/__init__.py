"""Scheduling (reference: service-schedule-management)."""

from sitewhere_tpu.schedule.cron import CronError, CronExpression
from sitewhere_tpu.schedule.manager import (
    BatchCommandInvocationJobExecutor, CommandInvocationJobExecutor,
    ScheduleManagement, ScheduleManager)

__all__ = ["BatchCommandInvocationJobExecutor", "CommandInvocationJobExecutor",
           "CronError", "CronExpression", "ScheduleManagement",
           "ScheduleManager"]
