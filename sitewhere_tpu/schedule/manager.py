"""Schedule management: CRUD + trigger engine + job execution.

Reference: service-schedule-management — QuartzScheduleManager.java wires
ISchedule triggers (cron/simple) to jobs (jobs/CommandInvocationJob.java,
jobs/BatchCommandInvocationJob.java) that fire command invocations through
event management. Here the Quartz scheduler is a single timer thread
computing next-fire times from CronExpression / simple intervals.
"""

from __future__ import annotations

import heapq
import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.common import (
    SearchCriteria, SearchResults, now_ms)
from sitewhere_tpu.model.event import (
    CommandInitiator, CommandTarget, DeviceCommandInvocation)
from sitewhere_tpu.model.schedule import (
    JobConstants, Schedule, ScheduledJob, ScheduledJobState, ScheduledJobType,
    TriggerConstants, TriggerType)
from sitewhere_tpu.registry.store import InMemoryStore, _Collection
from sitewhere_tpu.schedule.cron import CronExpression
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

LOGGER = logging.getLogger("sitewhere.schedule")


class ScheduleManagement:
    """Persistence API (IScheduleManagement)."""

    def __init__(self, store=None):
        store = store or InMemoryStore()
        self.schedules: _Collection[Schedule] = _Collection(
            "schedule", Schedule, store, ErrorCode.INVALID_SCHEDULE_TOKEN)
        self.jobs: _Collection[ScheduledJob] = _Collection(
            "scheduled_job", ScheduledJob, store,
            ErrorCode.INVALID_SCHEDULE_TOKEN)

    def create_schedule(self, schedule: Schedule) -> Schedule:
        if schedule.trigger_type == TriggerType.CRON:
            # validate eagerly, like Quartz does at scheduling time
            CronExpression(schedule.trigger_configuration.get(
                TriggerConstants.CRON_EXPRESSION, ""))
        return self.schedules.create(schedule)

    def get_schedule_by_token(self, token: str) -> Schedule:
        return self.schedules.require_by_token(token)

    def list_schedules(self, criteria: Optional[SearchCriteria] = None
                       ) -> SearchResults[Schedule]:
        return self.schedules.list(criteria)

    def delete_schedule(self, token: str) -> Schedule:
        entity = self.schedules.require_by_token(token)
        return self.schedules.delete(entity.id)

    def create_scheduled_job(self, job: ScheduledJob) -> ScheduledJob:
        self.schedules.require_by_token(job.schedule_token)
        return self.jobs.create(job)

    def get_scheduled_job_by_token(self, token: str) -> ScheduledJob:
        return self.jobs.require_by_token(token)

    def list_scheduled_jobs(self, criteria: Optional[SearchCriteria] = None
                            ) -> SearchResults[ScheduledJob]:
        return self.jobs.list(criteria)

    def delete_scheduled_job(self, token: str) -> ScheduledJob:
        entity = self.jobs.require_by_token(token)
        return self.jobs.delete(entity.id)


class CommandInvocationJobExecutor:
    """jobs/CommandInvocationJob.java: fire one command invocation from
    job configuration (assignment token, command token, param_* values)."""

    def __init__(self, registry, events):
        self.registry = registry
        self.events = events

    def execute(self, job: ScheduledJob) -> None:
        config = job.job_configuration
        assignment_token = config.get(JobConstants.ASSIGNMENT_TOKEN, "")
        command_token = config.get(JobConstants.COMMAND_TOKEN, "")
        parameters = {k[len(JobConstants.PARAMETER_PREFIX):]: v
                      for k, v in config.items()
                      if k.startswith(JobConstants.PARAMETER_PREFIX)}
        self.events.add_command_invocations(
            assignment_token, DeviceCommandInvocation(
                initiator=CommandInitiator.SCHEDULER, initiator_id=job.token,
                target=CommandTarget.ASSIGNMENT, target_id=assignment_token,
                command_token=command_token, parameter_values=parameters))


class BatchCommandInvocationJobExecutor:
    """jobs/BatchCommandInvocationJob.java: materialize + run a batch
    command invocation across devices selected by criteria_* filters."""

    def __init__(self, registry, batch_manager, batch_management):
        self.registry = registry
        self.batch_manager = batch_manager
        self.batch = batch_management

    def _select_devices(self, config: Dict[str, str]) -> List[str]:
        device_type_token = config.get(
            JobConstants.CRITERIA_PREFIX + "deviceTypeToken", "")
        tokens = []
        for device in self.registry.devices.all():
            if device_type_token:
                dtype = self.registry.get_device_type(device.device_type_id)
                if dtype is None or dtype.token != device_type_token:
                    continue
            tokens.append(device.token)
        return tokens

    def execute(self, job: ScheduledJob) -> None:
        from sitewhere_tpu.batch.manager import batch_command_invocation_request
        config = job.job_configuration
        parameters = {k[len(JobConstants.PARAMETER_PREFIX):]: v
                      for k, v in config.items()
                      if k.startswith(JobConstants.PARAMETER_PREFIX)}
        operation = batch_command_invocation_request(
            config.get(JobConstants.COMMAND_TOKEN, ""), parameters,
            self._select_devices(config))
        self.batch.create_batch_operation(operation, self.registry)
        self.batch_manager.process(operation)


class ScheduleManager(LifecycleComponent):
    """Trigger engine (QuartzScheduleManager equivalent): one timer thread,
    min-heap of (next_fire_ms, job_token)."""

    def __init__(self, management: ScheduleManagement,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__("schedule-manager")
        self.management = management
        self.executors: Dict[ScheduledJobType, object] = {}
        self._heap: List[Tuple[int, int, str]] = []  # (fire_ms, seq, token)
        self._fired_count: Dict[str, int] = {}
        self._seq = 0
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        m = (metrics or MetricsRegistry()).scoped("schedule")
        self.fired_counter = m.counter("jobs_fired")
        self.failed_counter = m.counter("jobs_failed")

    def register_executor(self, job_type: ScheduledJobType,
                          executor) -> None:
        self.executors[job_type] = executor

    # -- scheduling --------------------------------------------------------
    def _next_fire(self, schedule: Schedule, after_ms: int,
                   fired: int) -> Optional[int]:
        start = schedule.start_date or 0
        after_ms = max(after_ms, start - 1)
        if schedule.trigger_type == TriggerType.CRON:
            expression = CronExpression(schedule.trigger_configuration.get(
                TriggerConstants.CRON_EXPRESSION, ""))
            fire = expression.next_fire(after_ms)
        else:
            interval = int(schedule.trigger_configuration.get(
                TriggerConstants.REPEAT_INTERVAL, "0"))
            repeat = int(schedule.trigger_configuration.get(
                TriggerConstants.REPEAT_COUNT, "-1"))
            if repeat >= 0 and fired > repeat:
                return None
            if fired == 0:
                fire = max(start, after_ms + 1) if start else after_ms + 1
            elif interval <= 0:
                return None
            else:
                fire = after_ms + interval
        if schedule.end_date and fire > schedule.end_date:
            return None
        return fire

    def submit(self, job: ScheduledJob) -> None:
        """Activate a job (scheduleJob in the reference)."""
        schedule = self.management.get_schedule_by_token(job.schedule_token)
        fire = self._next_fire(schedule, now_ms(), 0)
        if fire is None:
            return
        self.management.jobs.update(
            job.id, {"job_state": ScheduledJobState.ACTIVE})
        with self._cv:
            self._seq += 1
            self._fired_count[job.token] = 0
            heapq.heappush(self._heap, (fire, self._seq, job.token))
            self._cv.notify()

    def unschedule(self, job_token: str) -> None:
        with self._cv:
            self._heap = [(f, s, t) for f, s, t in self._heap
                          if t != job_token]
            heapq.heapify(self._heap)

    # -- lifecycle ---------------------------------------------------------
    def on_start(self, monitor) -> None:
        self._stop = False
        self._thread = threading.Thread(target=self._run, name="scheduler",
                                        daemon=True)
        self._thread.start()
        # resubmit jobs that were active before restart
        for job in self.management.jobs.all():
            if job.job_state == ScheduledJobState.ACTIVE:
                self.submit(job)

    def on_stop(self, monitor) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- engine ------------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                if self._stop:
                    return
                now = now_ms()
                if not self._heap:
                    self._cv.wait(1.0)
                    continue
                fire, _, token = self._heap[0]
                if fire > now:
                    self._cv.wait(min((fire - now) / 1000.0, 1.0))
                    continue
                heapq.heappop(self._heap)
            self._fire_job(token, fire)

    def _fire_job(self, token: str, fire_ms: int) -> None:
        job = self.management.jobs.get_by_token(token)
        if job is None or job.job_state != ScheduledJobState.ACTIVE:
            return
        executor = self.executors.get(job.job_type)
        if executor is None:
            self.failed_counter.inc()
            LOGGER.warning("no executor for job type %s", job.job_type)
            return
        try:
            executor.execute(job)
            self.fired_counter.inc()
        except Exception:
            self.failed_counter.inc()
            LOGGER.exception("scheduled job %s failed", token)
        fired = self._fired_count.get(token, 0) + 1
        self._fired_count[token] = fired
        schedule = self.management.schedules.get_by_token(job.schedule_token)
        next_fire = (self._next_fire(schedule, fire_ms, fired)
                     if schedule else None)
        if next_fire is None:
            self.management.jobs.update(
                job.id, {"job_state": ScheduledJobState.COMPLETE})
            return
        with self._cv:
            self._seq += 1
            heapq.heappush(self._heap, (next_fire, self._seq, token))
            self._cv.notify()
