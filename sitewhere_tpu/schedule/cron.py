"""5-field cron expression parser + next-fire computation.

Replaces the Quartz trigger engine behind the reference's
QuartzScheduleManager (service-schedule-management). Supports the standard
minute/hour/day-of-month/month/day-of-week grammar: ``*``, lists ``1,2,3``,
ranges ``1-5``, and steps ``*/15`` / ``2-10/2``. Day-of-week 0 and 7 both
mean Sunday.
"""

from __future__ import annotations

import calendar
from datetime import datetime, timedelta
from typing import List, Set


class CronError(ValueError):
    pass


_FIELD_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 7)]


def _parse_field(spec: str, lo: int, hi: int) -> Set[int]:
    values: Set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronError(f"bad step '{step_s}'")
            if step < 1:
                raise CronError(f"bad step {step}")
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            try:
                start, end = int(a), int(b)
            except ValueError:
                raise CronError(f"bad range '{part}'")
        else:
            try:
                start = end = int(part)
            except ValueError:
                raise CronError(f"bad value '{part}'")
        if start < lo or end > hi or start > end:
            raise CronError(f"value out of range [{lo},{hi}]: '{part}'")
        values.update(range(start, end + 1, step))
    return values


class CronExpression:
    def __init__(self, expression: str):
        fields = expression.split()
        if len(fields) != 5:
            raise CronError(
                f"expected 5 fields (min hour dom mon dow), got '{expression}'")
        self.expression = expression
        parsed: List[Set[int]] = []
        for spec, (lo, hi) in zip(fields, _FIELD_RANGES):
            parsed.append(_parse_field(spec, lo, hi))
        self.minutes, self.hours, self.dom, self.months, dow = parsed
        self.dow = {d % 7 for d in dow}  # 7 == 0 == Sunday
        # standard cron: if both dom and dow are restricted, either matches
        self.dom_restricted = self.dom != set(range(1, 32))
        self.dow_restricted = self.dow != set(range(0, 7))

    def _day_matches(self, when: datetime) -> bool:
        # Python weekday(): Monday=0; cron: Sunday=0
        cron_dow = (when.weekday() + 1) % 7
        dom_ok = when.day in self.dom
        dow_ok = cron_dow in self.dow
        if self.dom_restricted and self.dow_restricted:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def matches(self, when: datetime) -> bool:
        return (when.minute in self.minutes and when.hour in self.hours
                and when.month in self.months and self._day_matches(when))

    def next_fire(self, after_ms: int) -> int:
        """Next firing time (epoch ms) strictly after `after_ms`."""
        when = datetime.fromtimestamp(after_ms / 1000.0)
        when = when.replace(second=0, microsecond=0) + timedelta(minutes=1)
        # bounded scan: cron repeats within 4 years (leap cycle)
        for _ in range(4 * 366 * 24 * 60):
            if self.matches(when):
                return int(when.timestamp() * 1000)
            when += timedelta(minutes=1)
        raise CronError(f"'{self.expression}' never fires")
