"""Bulk device operations (reference: service-batch-operations)."""

from sitewhere_tpu.batch.manager import (
    BatchCommandInvocationHandler, BatchManagement, BatchOperationManager,
    batch_command_invocation_request)

__all__ = ["BatchCommandInvocationHandler", "BatchManagement",
           "BatchOperationManager", "batch_command_invocation_request"]
