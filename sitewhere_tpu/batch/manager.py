"""Batch operations: bulk actions fanned out over device lists.

Reference: service-batch-operations — gRPC BatchManagementImpl (CRUD over
IBatchOperation/IBatchElement), BatchOperationManager.java:46 (throttled
executor :55 working through elements, updating per-element status), and
handler/BatchCommandInvocationHandler.java (one command invocation per
device, resolved against its active assignment).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Protocol

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.batch import (
    BatchElement, BatchOperation, BatchOperationStatus, BatchOperationTypes,
    ElementProcessingStatus)
from sitewhere_tpu.model.common import (
    SearchCriteria, SearchResults, new_id, now_ms, page)
from sitewhere_tpu.model.event import (
    CommandInitiator, CommandTarget, DeviceCommandInvocation)
from sitewhere_tpu.registry.store import InMemoryStore, _Collection
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

LOGGER = logging.getLogger("sitewhere.batch")


def batch_command_invocation_request(
        command_token: str, parameters: Dict[str, str],
        device_tokens: List[str], token: str = "") -> BatchOperation:
    """Build an InvokeCommand batch operation
    (BatchSpecUtils.createBatchCommandInvocation)."""
    return BatchOperation(
        token=token or new_id(),
        operation_type=BatchOperationTypes.INVOKE_COMMAND,
        parameters={"commandToken": command_token,
                    **{f"param_{k}": v for k, v in parameters.items()}},
        device_tokens=list(device_tokens))


class BatchManagement:
    """Persistence API for batch operations (IBatchManagement)."""

    def __init__(self, store=None):
        store = store or InMemoryStore()
        self.operations: _Collection[BatchOperation] = _Collection(
            "batch_operation", BatchOperation, store,
            ErrorCode.INVALID_BATCH_OPERATION_TOKEN)
        self.elements: _Collection[BatchElement] = _Collection(
            "batch_element", BatchElement, store,
            ErrorCode.INVALID_BATCH_OPERATION_TOKEN)

    def create_batch_operation(self, operation: BatchOperation,
                               registry=None) -> BatchOperation:
        """Create the operation + one element per device
        (BatchManagementImpl.createBatchOperation)."""
        created = self.operations.create(operation)
        for token in operation.device_tokens:
            device_id = token
            if registry is not None:
                device = registry.get_device_by_token(token)
                # unknown token: keep the element with the unresolved token as
                # its device_id — the handler fails it, surfacing the missing
                # device in the operation's FINISHED_WITH_ERRORS status
                device_id = device.id if device is not None else token
            self.elements.create(BatchElement(
                token=new_id(), batch_operation_id=created.id,
                device_id=device_id, metadata={"deviceToken": token}))
        return created

    def get_batch_operation_by_token(self, token: str) -> BatchOperation:
        return self.operations.require_by_token(token)

    def list_batch_operations(self, criteria: Optional[SearchCriteria] = None
                              ) -> SearchResults[BatchOperation]:
        return self.operations.list(criteria)

    def list_batch_elements(self, operation_token: str,
                            criteria: Optional[SearchCriteria] = None
                            ) -> SearchResults[BatchElement]:
        operation = self.operations.require_by_token(operation_token)
        items = [e for e in self.elements.all()
                 if e.batch_operation_id == operation.id]
        return page(items, criteria or SearchCriteria())

    def update_operation_status(self, operation_id: str,
                                status: BatchOperationStatus) -> None:
        updates: Dict = {"processing_status": status}
        if status == BatchOperationStatus.INITIALIZING:
            updates["processing_started_date"] = now_ms()
        elif status in (BatchOperationStatus.FINISHED_SUCCESSFULLY,
                        BatchOperationStatus.FINISHED_WITH_ERRORS):
            updates["processing_ended_date"] = now_ms()
        self.operations.update(operation_id, updates)

    def update_element_status(self, element: BatchElement,
                              status: ElementProcessingStatus,
                              metadata: Optional[Dict[str, str]] = None) -> None:
        updates: Dict = {"processing_status": status,
                         "processed_date": now_ms()}
        if metadata:
            updates["metadata"] = {**element.metadata, **metadata}
        self.elements.update(element.id, updates)


class OperationHandler(Protocol):
    """Per-element work (IBatchOperationHandler): returns result metadata."""

    def process(self, operation: BatchOperation,
                element: BatchElement) -> Dict[str, str]: ...


class BatchCommandInvocationHandler:
    """Create one DeviceCommandInvocation per element, persisted through
    event management against the device's active assignment
    (BatchCommandInvocationHandler.java)."""

    def __init__(self, registry, events):
        self.registry = registry
        self.events = events

    def process(self, operation: BatchOperation,
                element: BatchElement) -> Dict[str, str]:
        command_token = operation.parameters.get("commandToken", "")
        command = self.registry.device_commands.get_by_token(command_token)
        if command is None:
            raise SiteWhereError(f"unknown command '{command_token}'",
                                 ErrorCode.INVALID_COMMAND_TOKEN)
        device = self.registry.devices.get(element.device_id)
        if device is None:
            raise SiteWhereError("unknown device in batch element")
        assignment = self.registry.get_active_assignment(device.id)
        if assignment is None:
            raise SiteWhereError(f"device '{device.token}' not assigned",
                                 ErrorCode.DEVICE_NOT_ASSIGNED)
        parameters = {k[len("param_"):]: v
                      for k, v in operation.parameters.items()
                      if k.startswith("param_")}
        invocation = DeviceCommandInvocation(
            initiator=CommandInitiator.BATCH_OPERATION,
            initiator_id=operation.token, target=CommandTarget.ASSIGNMENT,
            target_id=assignment.token, command_token=command.token,
            device_command_id=command.id, parameter_values=parameters)
        persisted = self.events.add_command_invocations(assignment.token,
                                                        invocation)
        return {"invocationId": persisted[0].id}


class BatchOperationManager(LifecycleComponent):
    """Works through batch operations with optional throttling
    (BatchOperationManager.java:46, throttle :55)."""

    def __init__(self, batch: BatchManagement,
                 throttle_delay_ms: int = 0,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__("batch-operation-manager")
        self.batch = batch
        self.throttle_delay_ms = throttle_delay_ms
        self.handlers: Dict[str, OperationHandler] = {}
        m = (metrics or MetricsRegistry()).scoped("batch")
        self.processed_counter = m.counter("elements_processed")
        self.failed_counter = m.counter("elements_failed")

    def register_handler(self, operation_type: str,
                         handler: OperationHandler) -> None:
        self.handlers[operation_type] = handler

    def process(self, operation: BatchOperation) -> BatchOperation:
        """Process all elements synchronously; returns the finished op."""
        handler = self.handlers.get(operation.operation_type)
        if handler is None:
            raise SiteWhereError(
                f"no handler for operation type '{operation.operation_type}'")
        self.batch.update_operation_status(operation.id,
                                           BatchOperationStatus.INITIALIZING)
        elements = [e for e in self.batch.elements.all()
                    if e.batch_operation_id == operation.id]
        errors = 0
        for element in elements:
            self.batch.update_element_status(element,
                                             ElementProcessingStatus.PROCESSING)
            try:
                result = handler.process(operation, element)
                self.batch.update_element_status(
                    element, ElementProcessingStatus.SUCCEEDED, result)
                self.processed_counter.inc()
            except Exception as exc:
                errors += 1
                self.failed_counter.inc()
                self.batch.update_element_status(
                    element, ElementProcessingStatus.FAILED,
                    {"error": str(exc)})
            if self.throttle_delay_ms:
                time.sleep(self.throttle_delay_ms / 1000.0)
        status = (BatchOperationStatus.FINISHED_WITH_ERRORS if errors
                  else BatchOperationStatus.FINISHED_SUCCESSFULLY)
        self.batch.update_operation_status(operation.id, status)
        return self.batch.operations.get(operation.id)

    def submit(self, operation: BatchOperation) -> threading.Thread:
        """Async processing on a worker thread (the reference's executor)."""
        thread = threading.Thread(target=self.process, args=(operation,),
                                  name=f"batch-{operation.token}", daemon=True)
        thread.start()
        return thread
