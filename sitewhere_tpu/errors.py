"""Framework error model.

Mirrors the reference's SiteWhereException / SiteWhereSystemException + ErrorCode
surface (reference: sitewhere-core-api/src/main/java/com/sitewhere/spi/
SiteWhereException.java and spi/error/ErrorCode.java) as a Python exception
hierarchy with stable numeric codes for API responses.
"""

from __future__ import annotations

import enum


class ErrorCode(enum.IntEnum):
    """Stable numeric error codes exposed over the REST API.

    Subset of the reference's spi/error/ErrorCode.java enum, keeping the same
    semantic groupings (1xx auth, 5xx invalid ids, 8xx invalid state).
    """

    INVALID_USERNAME = 100
    INVALID_PASSWORD = 101
    DUPLICATE_USER = 102
    NOT_AUTHORIZED = 103
    INVALID_TENANT_TOKEN = 104

    INVALID_DEVICE_TOKEN = 500
    INVALID_DEVICE_TYPE_TOKEN = 501
    INVALID_AREA_TOKEN = 502
    INVALID_ZONE_TOKEN = 503
    INVALID_CUSTOMER_TOKEN = 504
    INVALID_ASSET_TOKEN = 505
    INVALID_ASSIGNMENT_TOKEN = 506
    INVALID_EVENT_ID = 507
    INVALID_COMMAND_TOKEN = 508
    INVALID_GROUP_TOKEN = 509
    INVALID_SCHEDULE_TOKEN = 510
    INVALID_BATCH_OPERATION_TOKEN = 511
    INVALID_STREAM_ID = 512

    DUPLICATE_TOKEN = 600
    DUPLICATE_STREAM_ID = 601

    DEVICE_ALREADY_ASSIGNED = 800
    DEVICE_NOT_ASSIGNED = 801
    DEVICE_TYPE_IN_USE = 802
    REGISTRATION_DISABLED = 803
    MALFORMED_EVENT = 804
    CAPACITY_EXCEEDED = 805

    GENERIC = 9999


class SiteWhereError(Exception):
    """Base framework error (reference: SiteWhereException.java)."""

    def __init__(self, message: str, code: ErrorCode = ErrorCode.GENERIC,
                 http_status: int = 400):
        super().__init__(message)
        self.code = code
        self.http_status = http_status


class NotFoundError(SiteWhereError):
    def __init__(self, message: str, code: ErrorCode):
        super().__init__(message, code, http_status=404)


class DuplicateTokenError(SiteWhereError):
    def __init__(self, message: str, code: ErrorCode = ErrorCode.DUPLICATE_TOKEN):
        super().__init__(message, code, http_status=409)


class AuthError(SiteWhereError):
    def __init__(self, message: str, code: ErrorCode = ErrorCode.NOT_AUTHORIZED):
        super().__init__(message, code, http_status=401)


class InvalidStateError(SiteWhereError):
    pass


class LifecycleError(SiteWhereError):
    """A component failed a lifecycle transition (reference: lifecycle error states)."""
    pass
