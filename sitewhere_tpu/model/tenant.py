"""Tenant model (sitewhere-core-api spi/tenant/ITenant.java)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from sitewhere_tpu.model.common import BrandedEntity


@dataclass
class Tenant(BrandedEntity):
    """Isolated customer account (ITenant). `authentication_token` is the
    tenant token clients pass per request; `authorized_user_ids` gates access;
    `tenant_template_id` selects the bootstrap template (dataset + scripts)."""

    authentication_token: str = ""
    logo_url: str = ""
    authorized_user_ids: List[str] = field(default_factory=list)
    tenant_template_id: str = "default"
    dataset_template_id: str = "empty"
