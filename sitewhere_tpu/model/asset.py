"""Asset model (sitewhere-core-api spi/asset/IAsset.java, IAssetType.java).

Assets are the people/hardware/locations bound to device assignments."""

from __future__ import annotations

import enum
from dataclasses import dataclass

from sitewhere_tpu.model.common import BrandedEntity


class AssetCategory(enum.Enum):
    """Asset classification (reference AssetCategory)."""

    DEVICE = "Device"
    PERSON = "Person"
    HARDWARE = "Hardware"


@dataclass
class AssetType(BrandedEntity):
    """Class of assets (IAssetType)."""

    asset_category: AssetCategory = AssetCategory.DEVICE


@dataclass
class Asset(BrandedEntity):
    """Asset instance (IAsset)."""

    asset_type_id: str = ""
