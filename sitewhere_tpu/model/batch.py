"""Batch operation model (sitewhere-core-api spi/batch/IBatchOperation.java,
IBatchElement.java): bulk actions fanned out across many devices."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sitewhere_tpu.model.common import PersistentEntity


class BatchOperationStatus(enum.Enum):
    UNPROCESSED = "Unprocessed"
    INITIALIZING = "Initializing"
    INITIALIZED_SUCCESSFULLY = "InitializedSuccessfully"
    INITIALIZED_WITH_ERRORS = "InitializedWithErrors"
    FINISHED_SUCCESSFULLY = "FinishedSuccessfully"
    FINISHED_WITH_ERRORS = "FinishedWithErrors"


class ElementProcessingStatus(enum.Enum):
    UNPROCESSED = "Unprocessed"
    INITIALIZED = "Initialized"
    PROCESSING = "Processing"
    FAILED = "Failed"
    SUCCEEDED = "Succeeded"


class BatchOperationTypes:
    """Well-known operation types (reference BatchOperationTypes)."""

    INVOKE_COMMAND = "InvokeCommand"


@dataclass
class BatchOperation(PersistentEntity):
    """Bulk operation over a device list (IBatchOperation)."""

    operation_type: str = BatchOperationTypes.INVOKE_COMMAND
    parameters: Dict[str, str] = field(default_factory=dict)
    device_tokens: List[str] = field(default_factory=list)
    processing_status: BatchOperationStatus = BatchOperationStatus.UNPROCESSED
    processing_started_date: Optional[int] = None
    processing_ended_date: Optional[int] = None


@dataclass
class BatchElement(PersistentEntity):
    """Per-device element of a batch operation (IBatchElement)."""

    batch_operation_id: str = ""
    device_id: str = ""
    processing_status: ElementProcessingStatus = ElementProcessingStatus.UNPROCESSED
    processed_date: Optional[int] = None
    metadata: Dict[str, str] = field(default_factory=dict)
