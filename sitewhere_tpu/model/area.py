"""Location hierarchy model: customers, areas, zones.

Reference surface: sitewhere-core-api spi/area/ (IArea, IAreaType, IZone) and
spi/customer/ (ICustomer, ICustomerType).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from sitewhere_tpu.model.common import BrandedEntity, Location


@dataclass
class CustomerType(BrandedEntity):
    """Class of customers (ICustomerType)."""

    contained_customer_type_ids: List[str] = field(default_factory=list)


@dataclass
class Customer(BrandedEntity):
    """Customer in the containment hierarchy (ICustomer)."""

    customer_type_id: str = ""
    parent_customer_id: str = ""


@dataclass
class AreaType(BrandedEntity):
    """Class of areas (IAreaType)."""

    contained_area_type_ids: List[str] = field(default_factory=list)


@dataclass
class Area(BrandedEntity):
    """Physical/logical area devices are assigned to (IArea). The bounds
    polygon drives map display; zones within the area drive geofencing."""

    area_type_id: str = ""
    parent_area_id: str = ""
    bounds: List[Location] = field(default_factory=list)


@dataclass
class Zone(BrandedEntity):
    """Geofence polygon within an area (IZone).

    TPU note: zones are compiled into the padded vertex tensor consumed by the
    vectorized point-in-polygon kernel (ops/geofence.py) — the JTS
    poly.contains() of the reference's ZoneTestRuleProcessor.java:47-52 becomes
    a crossing-number test over all zones at once.
    """

    area_id: str = ""
    bounds: List[Location] = field(default_factory=list)
    border_color: str = "#000000"
    fill_color: str = "#dddddd"
    opacity: float = 0.3
