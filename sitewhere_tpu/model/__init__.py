"""Domain model: the L0 API contract.

Python dataclass equivalents of the reference's com.sitewhere.spi.* surface
(reference: sitewhere-core-api, 519 files). Every persisted entity carries a
uuid `id`, a human `token`, timestamps and a metadata map, mirroring
IPersistentEntity / IMetadataProvider.
"""

from sitewhere_tpu.model.common import (
    PersistentEntity,
    BrandedEntity,
    Pager,
    SearchCriteria,
    SearchResults,
    DateRangeCriteria,
    Location,
)
from sitewhere_tpu.model.device import (
    Device,
    DeviceType,
    DeviceAssignment,
    DeviceAssignmentStatus,
    DeviceCommand,
    CommandParameter,
    ParameterType,
    DeviceStatus,
    DeviceGroup,
    DeviceGroupElement,
    DeviceAlarm,
    DeviceAlarmState,
    DeviceElementMapping,
    DeviceElementSchema,
    DeviceSlot,
    DeviceUnit,
    find_device_slot,
    DeviceStream,
)
from sitewhere_tpu.model.area import (
    AreaType,
    Area,
    Zone,
    CustomerType,
    Customer,
)
from sitewhere_tpu.model.event import (
    DeviceEvent,
    DeviceEventType,
    DeviceMeasurement,
    DeviceLocation,
    DeviceAlert,
    AlertLevel,
    AlertSource,
    DeviceCommandInvocation,
    CommandInitiator,
    CommandTarget,
    DeviceCommandResponse,
    DeviceStateChange,
    DeviceStreamData,
    DeviceEventBatch,
    DeviceEventContext,
    DeviceRegistrationRequest,
)
from sitewhere_tpu.model.state import DeviceState, PresenceState
from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.model.user import User, GrantedAuthority, ACCOUNT_STATUS
from sitewhere_tpu.model.asset import Asset, AssetType, AssetCategory
from sitewhere_tpu.model.batch import (
    BatchOperation,
    BatchOperationStatus,
    BatchElement,
    ElementProcessingStatus,
)
from sitewhere_tpu.model.schedule import (
    Schedule,
    ScheduledJob,
    TriggerType,
    ScheduledJobType,
    ScheduledJobState,
)

__all__ = [name for name in dir() if not name.startswith("_")]
