"""Device event model: the payloads of the hot path.

Reference surface: sitewhere-core-api spi/device/event/ — IDeviceEvent,
IDeviceMeasurement, IDeviceLocation, IDeviceAlert, IDeviceCommandInvocation,
IDeviceCommandResponse, IDeviceStateChange, IDeviceStreamData, DeviceEventType.

Design note (TPU-first): these dataclasses are the *control-plane/API* view.
On the hot path events never exist as Python objects per-event; they are packed
straight into the SoA tensor schema in sitewhere_tpu.ops.pack (one fixed-width
column per field below) and only materialized back into dataclasses at the API
edge. Keep the two in sync: ops/pack.py cites this file.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from sitewhere_tpu.model.common import PersistentEntity, new_id, now_ms


class DeviceEventType(enum.IntEnum):
    """Event discriminator (spi/device/event/DeviceEventType.java).

    Integer-valued: the same codes are used in the packed `event_type` tensor
    column on device.
    """

    MEASUREMENT = 0
    LOCATION = 1
    ALERT = 2
    COMMAND_INVOCATION = 3
    COMMAND_RESPONSE = 4
    STATE_CHANGE = 5
    STREAM_DATA = 6


class AlertSource(enum.IntEnum):
    DEVICE = 0
    SYSTEM = 1


class AlertLevel(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2
    CRITICAL = 3


class CommandInitiator(enum.IntEnum):
    REST = 0
    BATCH_OPERATION = 1
    SCRIPT = 2
    SCHEDULER = 3


class CommandTarget(enum.IntEnum):
    ASSIGNMENT = 0


@dataclass
class DeviceEvent:
    """Base event (IDeviceEvent): identity + routing context + two timestamps.

    `event_date` is when the event happened on the device; `received_date` is
    when the platform ingested it (IDeviceEvent.getEventDate/getReceivedDate).
    """

    id: str = field(default_factory=new_id)
    alternate_id: str = ""  # client-supplied id used for deduplication
    event_type: DeviceEventType = DeviceEventType.MEASUREMENT
    device_id: str = ""
    device_assignment_id: str = ""
    customer_id: str = ""
    area_id: str = ""
    asset_id: str = ""
    event_date: int = field(default_factory=now_ms)
    received_date: int = field(default_factory=now_ms)
    metadata: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        from sitewhere_tpu.model.common import _asdict
        d = _asdict(self)
        d["eventType"] = DeviceEventType(self.event_type).name
        return d


@dataclass
class DeviceMeasurement(DeviceEvent):
    """Named scalar sample (IDeviceMeasurement)."""

    event_type: DeviceEventType = DeviceEventType.MEASUREMENT
    name: str = ""
    value: float = 0.0


@dataclass
class DeviceLocation(DeviceEvent):
    """Geo fix (IDeviceLocation)."""

    event_type: DeviceEventType = DeviceEventType.LOCATION
    latitude: float = 0.0
    longitude: float = 0.0
    elevation: float = 0.0


@dataclass
class DeviceAlert(DeviceEvent):
    """Alert raised by device or system (IDeviceAlert)."""

    event_type: DeviceEventType = DeviceEventType.ALERT
    source: AlertSource = AlertSource.DEVICE
    level: AlertLevel = AlertLevel.INFO
    type: str = ""  # alert type code, e.g. "zone.violation"
    message: str = ""


@dataclass
class DeviceCommandInvocation(DeviceEvent):
    """Cloud->device command call (IDeviceCommandInvocation)."""

    event_type: DeviceEventType = DeviceEventType.COMMAND_INVOCATION
    initiator: CommandInitiator = CommandInitiator.REST
    initiator_id: str = ""
    target: CommandTarget = CommandTarget.ASSIGNMENT
    target_id: str = ""
    device_command_id: str = ""
    command_token: str = ""
    parameter_values: Dict[str, str] = field(default_factory=dict)


@dataclass
class DeviceCommandResponse(DeviceEvent):
    """Device ack/response to an invocation (IDeviceCommandResponse)."""

    event_type: DeviceEventType = DeviceEventType.COMMAND_RESPONSE
    originating_event_id: str = ""
    response_event_id: str = ""
    response: str = ""


@dataclass
class DeviceStateChange(DeviceEvent):
    """Registration/presence/state transition (IDeviceStateChange)."""

    event_type: DeviceEventType = DeviceEventType.STATE_CHANGE
    attribute: str = ""  # e.g. "presence", "registration"
    type: str = ""
    previous_state: str = ""
    new_state: str = ""


@dataclass
class DeviceStreamData(DeviceEvent):
    """Chunk of a binary device stream (IDeviceStreamData)."""

    event_type: DeviceEventType = DeviceEventType.STREAM_DATA
    stream_id: str = ""
    sequence_number: int = 0
    data: bytes = b""


@dataclass
class DeviceEventBatch:
    """Decoded inbound batch for one device (IDeviceEventBatch): what a
    decoder yields from one wire payload."""

    device_token: str = ""
    measurements: List[DeviceMeasurement] = field(default_factory=list)
    locations: List[DeviceLocation] = field(default_factory=list)
    alerts: List[DeviceAlert] = field(default_factory=list)

    def all_events(self) -> List[DeviceEvent]:
        return [*self.measurements, *self.locations, *self.alerts]


@dataclass
class DeviceEventContext:
    """Enrichment envelope added after persistence (IDeviceEventContext /
    GDeviceEventContext in device-event-model.proto:288-321): the device &
    assignment fields rule processors and connectors need, resolved once."""

    device_id: str = ""
    device_token: str = ""
    device_type_id: str = ""
    assignment_id: str = ""
    customer_id: str = ""
    area_id: str = ""
    asset_id: str = ""
    tenant_id: str = ""


@dataclass
class DeviceRegistrationRequest:
    """Device self-registration payload (IDeviceRegistrationRequest)."""

    device_token: str = ""
    device_type_token: str = ""
    area_token: str = ""
    customer_token: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


EVENT_CLASS_BY_TYPE = {
    DeviceEventType.MEASUREMENT: DeviceMeasurement,
    DeviceEventType.LOCATION: DeviceLocation,
    DeviceEventType.ALERT: DeviceAlert,
    DeviceEventType.COMMAND_INVOCATION: DeviceCommandInvocation,
    DeviceEventType.COMMAND_RESPONSE: DeviceCommandResponse,
    DeviceEventType.STATE_CHANGE: DeviceStateChange,
    DeviceEventType.STREAM_DATA: DeviceStreamData,
}

_EVENT_ENUM_FIELDS = {
    "event_type": DeviceEventType,
    "source": AlertSource,
    "level": AlertLevel,
    "initiator": CommandInitiator,
    "target": CommandTarget,
}


_EVENT_HOOK_BY_TYPE = {
    DeviceEventType.MEASUREMENT: "on_measurement",
    DeviceEventType.LOCATION: "on_location",
    DeviceEventType.ALERT: "on_alert",
    DeviceEventType.COMMAND_INVOCATION: "on_command_invocation",
    DeviceEventType.COMMAND_RESPONSE: "on_command_response",
    DeviceEventType.STATE_CHANGE: "on_state_change",
    DeviceEventType.STREAM_DATA: "on_stream_data",
}


def dispatch_event(handler: Any, context: Any, event: DeviceEvent) -> None:
    """Route an event to the handler's typed `on_*` hook (the per-type switch
    of KafkaRuleProcessorHost.attemptToProcess / outbound connector
    processors). Missing hooks are no-ops."""
    hook = getattr(handler, _EVENT_HOOK_BY_TYPE.get(event.event_type, ""),
                   None)
    if hook is not None:
        hook(context, event)


def event_from_dict(data: Dict[str, Any]) -> DeviceEvent:
    """Rebuild a concrete DeviceEvent from its `to_dict()` form.

    The inverse of the proto->API conversion the reference does in
    EventModelConverter when a consumer pulls a payload off a Kafka topic.
    Unknown keys (like the redundant "eventType" name) are dropped so payloads
    stay forward-compatible.
    """
    import dataclasses as _dc

    etype = DeviceEventType(data["event_type"])
    cls = EVENT_CLASS_BY_TYPE[etype]
    names = {f.name for f in _dc.fields(cls)}
    kwargs: Dict[str, Any] = {}
    for key, value in data.items():
        if key not in names:
            continue
        enum_cls = _EVENT_ENUM_FIELDS.get(key)
        if enum_cls is not None:
            value = enum_cls(value)
        kwargs[key] = value
    return cls(**kwargs)
