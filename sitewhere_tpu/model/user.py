"""User/authority model (sitewhere-core-api spi/user/IUser.java,
IGrantedAuthority.java). Passwords are stored as salted PBKDF2 hashes
(api/auth.py), replacing the reference's BCrypt."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from sitewhere_tpu.model.common import PersistentEntity


class ACCOUNT_STATUS:
    ACTIVE = "Active"
    EXPIRED = "Expired"
    LOCKED = "Locked"


class SiteWhereRoles:
    """Well-known authorities (reference: SiteWhereRoles.java / SiteWhereAuthority)."""

    REST = "REST"
    ADMINISTER_USERS = "ADMINISTER_USERS"
    ADMINISTER_TENANTS = "ADMINISTER_TENANTS"
    ADMINISTER_TENANT_SELF = "ADMINISTER_TENANT_SELF"
    VIEW_SERVER_INFO = "VIEW_SERVER_INFO"
    ADMINISTER_SCHEDULES = "ADMINISTER_SCHEDULES"

    ALL = [REST, ADMINISTER_USERS, ADMINISTER_TENANTS, ADMINISTER_TENANT_SELF,
           VIEW_SERVER_INFO, ADMINISTER_SCHEDULES]


@dataclass
class GrantedAuthority:
    """Named permission (IGrantedAuthority)."""

    authority: str = ""
    description: str = ""
    parent: str = ""
    group: bool = False


@dataclass
class User(PersistentEntity):
    """Platform user (IUser). `token` holds the username."""

    username: str = ""
    hashed_password: str = ""
    first_name: str = ""
    last_name: str = ""
    status: str = ACCOUNT_STATUS.ACTIVE
    last_login_date: Optional[int] = None
    authorities: List[str] = field(default_factory=list)
