"""Shared model base types: persistent entities, paging, search.

Reference surface: sitewhere-core-api spi/common/IPersistentEntity.java,
spi/search/ISearchCriteria.java, spi/search/ISearchResults.java.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Generic, Iterable, List, Optional, Sequence, TypeVar

T = TypeVar("T")


def new_id() -> str:
    return str(uuid.uuid4())


_now_ms_override = None  # test hook: deterministic replication-algebra clocks


def now_ms() -> int:
    if _now_ms_override is not None:
        return _now_ms_override()
    return int(time.time() * 1000)


@dataclass
class PersistentEntity:
    """Base for all persisted domain objects (IPersistentEntity + IMetadataProvider)."""

    id: str = field(default_factory=new_id)
    token: str = ""
    created_date: int = field(default_factory=now_ms)
    created_by: str = ""
    updated_date: Optional[int] = None
    updated_by: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)

    def touch(self, username: str = "") -> None:
        # monotonic past the current stamp: a host whose clock trails a
        # replicated update it already applied must still produce a NEWER
        # last-writer-wins stamp, or its local edit would lose everywhere
        # else while winning locally (cluster registry replication)
        self.updated_date = max(now_ms(),
                                (self.updated_date or self.created_date) + 1)
        self.updated_by = username

    def to_dict(self) -> Dict[str, Any]:
        return _asdict(self)


@dataclass
class BrandedEntity(PersistentEntity):
    """Entity with branding (IBrandedEntity): admin-UI presentation fields."""

    name: str = ""
    description: str = ""
    image_url: str = ""
    icon: str = ""
    background_color: str = ""
    foreground_color: str = ""
    border_color: str = ""


@dataclass(frozen=True)
class Location:
    """Geo point (ILocation)."""

    latitude: float
    longitude: float
    elevation: float = 0.0


@dataclass
class SearchCriteria:
    """Paging criteria (ISearchCriteria). Pages are 1-based like the reference."""

    page_number: int = 1
    page_size: int = 100

    @property
    def offset(self) -> int:
        return max(0, (self.page_number - 1) * self.page_size)


@dataclass
class DateRangeCriteria(SearchCriteria):
    """Paging + time window (IDateRangeSearchCriteria), ms epoch, inclusive."""

    start_date: Optional[int] = None
    end_date: Optional[int] = None

    def in_range(self, ts: int) -> bool:
        if self.start_date is not None and ts < self.start_date:
            return False
        if self.end_date is not None and ts > self.end_date:
            return False
        return True


@dataclass
class SearchResults(Generic[T]):
    """Page of results + total count (ISearchResults)."""

    results: List[T]
    num_results: int

    def to_dict(self) -> Dict[str, Any]:
        return {
            "numResults": self.num_results,
            "results": [_asdict(r) for r in self.results],
        }


class Pager(Generic[T]):
    """Applies SearchCriteria paging while counting total matches.

    Reference: sitewhere-core Pager.java — process every match, keep only the
    requested page.
    """

    def __init__(self, criteria: SearchCriteria):
        self._criteria = criteria
        self._matched = 0
        self._page: List[T] = []

    def process(self, item: T) -> None:
        self._matched += 1
        start = self._criteria.offset
        if start < self._matched <= start + self._criteria.page_size:
            self._page.append(item)

    def process_all(self, items: Iterable[T]) -> "Pager[T]":
        for item in items:
            self.process(item)
        return self

    def results(self) -> SearchResults[T]:
        return SearchResults(results=self._page, num_results=self._matched)


def _asdict(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _asdict(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {k: _asdict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_asdict(v) for v in obj]
    if isinstance(obj, (str, int, float, bool, bytes)) or obj is None:
        return obj  # bytes pass through: msgpack handles them natively
    if hasattr(obj, "value"):  # enums
        return obj.value
    return str(obj)


def page(items: Sequence[T], criteria: SearchCriteria) -> SearchResults[T]:
    """Page a pre-filtered sequence."""
    return Pager[T](criteria).process_all(items).results()
