"""Device state model: last-known values + presence.

Reference surface: sitewhere-grpc-device-state / service-device-state —
IDeviceState with last-interaction date, presence-missing date, and maps of
last measurement/location/alert per assignment
(DeviceStateProcessingLogic.java:116+).

TPU note: this dataclass is the API view; the authoritative state lives in the
HBM-resident DeviceStateTensors (pipeline/state_tensors.py) and is materialized
into DeviceState records on API reads.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional

from sitewhere_tpu.model.common import new_id


class PresenceState(enum.IntEnum):
    PRESENT = 1
    NOT_PRESENT = 0


@dataclass
class DeviceState:
    """Last-known state snapshot for one device assignment (IDeviceState)."""

    id: str = field(default_factory=new_id)
    device_id: str = ""
    device_assignment_id: str = ""
    device_type_id: str = ""
    customer_id: str = ""
    area_id: str = ""
    asset_id: str = ""
    last_interaction_date: Optional[int] = None
    presence_missing_date: Optional[int] = None
    presence: PresenceState = PresenceState.PRESENT
    # measurement name -> (event_date, value)
    last_measurements: Dict[str, tuple] = field(default_factory=dict)
    # (event_date, lat, lon, elevation)
    last_location: Optional[tuple] = None
    # alert type -> (event_date, level, message)
    last_alerts: Dict[str, tuple] = field(default_factory=dict)
