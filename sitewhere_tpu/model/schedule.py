"""Scheduling model (sitewhere-core-api spi/scheduling/ISchedule.java,
IScheduledJob.java): cron/simple triggers firing command invocations, replacing
the reference's Quartz integration (QuartzScheduleManager.java)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional

from sitewhere_tpu.model.common import PersistentEntity


class TriggerType(enum.Enum):
    CRON = "CronTrigger"
    SIMPLE = "SimpleTrigger"


class TriggerConstants:
    """Keys into Schedule.trigger_configuration (reference TriggerConstants)."""

    CRON_EXPRESSION = "cronExpression"  # 5-field cron
    REPEAT_INTERVAL = "repeatInterval"  # ms between firings (simple trigger)
    REPEAT_COUNT = "repeatCount"  # -1 = forever


class ScheduledJobType(enum.Enum):
    COMMAND_INVOCATION = "CommandInvocation"
    BATCH_COMMAND_INVOCATION = "BatchCommandInvocation"
    # unattended drift-refit sweeps (actuation/refit.py
    # DriftRefitJobExecutor) — no reference analogue; the adaptation
    # loop closed in-platform needs its own trigger type
    DRIFT_REFIT = "DriftRefit"


class ScheduledJobState(enum.Enum):
    UNSUBMITTED = "Unsubmitted"
    ACTIVE = "Active"
    COMPLETE = "Complete"


class JobConstants:
    """Keys into ScheduledJob.job_configuration (reference JobConstants)."""

    ASSIGNMENT_TOKEN = "assignmentToken"
    COMMAND_TOKEN = "commandToken"
    PARAMETER_PREFIX = "param_"
    CRITERIA_PREFIX = "criteria_"


@dataclass
class Schedule(PersistentEntity):
    """When to run (ISchedule)."""

    name: str = ""
    trigger_type: TriggerType = TriggerType.SIMPLE
    trigger_configuration: Dict[str, str] = field(default_factory=dict)
    start_date: Optional[int] = None
    end_date: Optional[int] = None


@dataclass
class ScheduledJob(PersistentEntity):
    """What to run on a schedule (IScheduledJob)."""

    schedule_token: str = ""
    job_type: ScheduledJobType = ScheduledJobType.COMMAND_INVOCATION
    job_configuration: Dict[str, str] = field(default_factory=dict)
    job_state: ScheduledJobState = ScheduledJobState.UNSUBMITTED
