"""Device registry model.

Reference surface: sitewhere-core-api spi/device/ — IDevice, IDeviceType,
IDeviceAssignment, IDeviceCommand, IDeviceStatus, IDeviceGroup, IDeviceAlarm,
IDeviceElementMapping, DeviceAssignmentStatus.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sitewhere_tpu.model.common import BrandedEntity, PersistentEntity


class DeviceContainerPolicy(enum.Enum):
    STANDALONE = "Standalone"
    COMPOSITE = "Composite"


@dataclass
class DeviceType(BrandedEntity):
    """Hardware/firmware class of devices (IDeviceType)."""

    container_policy: DeviceContainerPolicy = DeviceContainerPolicy.STANDALONE
    # For COMPOSITE types: named slots/units a child device can map into.
    device_element_schema: Dict[str, str] = field(default_factory=dict)


class ParameterType(enum.Enum):
    """Command parameter wire types (spi/device/command/ParameterType.java,
    mirroring protobuf scalar types)."""

    DOUBLE = "Double"
    FLOAT = "Float"
    INT32 = "Int32"
    INT64 = "Int64"
    UINT32 = "UInt32"
    UINT64 = "UInt64"
    SINT32 = "SInt32"
    SINT64 = "SInt64"
    FIXED32 = "Fixed32"
    FIXED64 = "Fixed64"
    SFIXED32 = "SFixed32"
    SFIXED64 = "SFixed64"
    BOOL = "Bool"
    STRING = "String"
    BYTES = "Bytes"


@dataclass
class CommandParameter:
    """One parameter of a device command (ICommandParameter)."""

    name: str = ""
    type: ParameterType = ParameterType.STRING
    required: bool = False


@dataclass
class DeviceCommand(PersistentEntity):
    """Command callable on devices of a type (IDeviceCommand)."""

    device_type_id: str = ""
    namespace: str = ""
    name: str = ""
    description: str = ""
    parameters: List[CommandParameter] = field(default_factory=list)


@dataclass
class DeviceStatus(PersistentEntity):
    """Named device status within a type's state machine (IDeviceStatus)."""

    device_type_id: str = ""
    code: str = ""
    name: str = ""
    background_color: str = ""
    foreground_color: str = ""
    border_color: str = ""
    icon: str = ""


@dataclass
class DeviceElementMapping:
    """Composite-device slot -> child device mapping (IDeviceElementMapping)."""

    device_element_schema_path: str = ""
    device_token: str = ""


@dataclass
class Device(PersistentEntity):
    """Registered device (IDevice)."""

    device_type_id: str = ""
    parent_device_id: str = ""  # set when mapped into a composite parent
    status: str = ""  # code of a DeviceStatus
    comments: str = ""
    device_element_mappings: List[DeviceElementMapping] = field(default_factory=list)


class DeviceAssignmentStatus(enum.IntEnum):
    """Assignment state machine (spi/device/DeviceAssignmentStatus.java).

    Integer-valued: mirrored into the registry lookup tensor
    (registry/tensors.py) so validation runs on device.
    """

    ACTIVE = 1
    MISSING = 2
    RELEASED = 3


@dataclass
class DeviceAssignment(PersistentEntity):
    """Binding of a device to customer/area/asset for a period (IDeviceAssignment).

    Events are always recorded against an assignment, not a raw device.
    """

    device_id: str = ""
    device_type_id: str = ""
    customer_id: str = ""
    area_id: str = ""
    asset_id: str = ""
    status: DeviceAssignmentStatus = DeviceAssignmentStatus.ACTIVE
    active_date: Optional[int] = None
    released_date: Optional[int] = None


class DeviceGroupRole:
    """Well-known group element roles (reference uses free-form role strings)."""

    GROUP = "group"
    DEVICE = "device"


@dataclass
class DeviceGroup(BrandedEntity):
    """Named set of devices/groups with roles (IDeviceGroup)."""

    roles: List[str] = field(default_factory=list)


@dataclass
class DeviceGroupElement(PersistentEntity):
    """Member of a device group (IDeviceGroupElement): device OR nested group."""

    group_id: str = ""
    device_id: str = ""
    nested_group_id: str = ""
    roles: List[str] = field(default_factory=list)


class DeviceAlarmState(enum.Enum):
    """Alarm lifecycle (spi/device/DeviceAlarmState.java)."""

    TRIGGERED = "Triggered"
    ACKNOWLEDGED = "Acknowledged"
    RESOLVED = "Resolved"


@dataclass
class DeviceAlarm(PersistentEntity):
    """Persistent alarm on a device (IDeviceAlarm), raised by rule processors."""

    device_id: str = ""
    device_assignment_id: str = ""
    customer_id: str = ""
    area_id: str = ""
    asset_id: str = ""
    alarm_message: str = ""
    triggering_event_id: str = ""
    state: DeviceAlarmState = DeviceAlarmState.TRIGGERED
    triggered_date: Optional[int] = None
    acknowledged_date: Optional[int] = None
    resolved_date: Optional[int] = None


@dataclass
class DeviceStream(PersistentEntity):
    """Binary stream declared by a device under an assignment (IDeviceStream,
    reference: sitewhere-core-api spi/device/streaming/IDeviceStream.java).
    `token` holds the stream id; chunks are DeviceStreamData events."""

    assignment_id: str = ""
    content_type: str = "application/octet-stream"
