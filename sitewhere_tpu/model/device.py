"""Device registry model.

Reference surface: sitewhere-core-api spi/device/ — IDevice, IDeviceType,
IDeviceAssignment, IDeviceCommand, IDeviceStatus, IDeviceGroup, IDeviceAlarm,
IDeviceElementMapping, DeviceAssignmentStatus.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sitewhere_tpu.model.common import BrandedEntity, PersistentEntity


class DeviceContainerPolicy(enum.Enum):
    STANDALONE = "Standalone"
    COMPOSITE = "Composite"


@dataclass
class DeviceSlot:
    """Position where a child device may insert into a composite parent
    (spi/device/element/IDeviceSlot.java). `path` is the slot's segment
    within its containing unit."""

    name: str = ""
    path: str = ""


@dataclass
class DeviceUnit:
    """Logical group of related slots and subordinate units
    (spi/device/element/IDeviceUnit.java). `path` is this unit's segment
    within its parent."""

    name: str = ""
    path: str = ""
    device_slots: List[DeviceSlot] = field(default_factory=list)
    device_units: List["DeviceUnit"] = field(default_factory=list)


@dataclass
class DeviceElementSchema(DeviceUnit):
    """Root unit of a composite type's nesting schema
    (spi/device/element/IDeviceElementSchema.java — an IDeviceUnit whose
    own path is empty; slot paths address through nested unit segments,
    e.g. "bus/slot1")."""


def find_device_slot(schema: Optional[DeviceElementSchema],
                     path: str) -> Optional[DeviceSlot]:
    """Walk a '/'-separated schema path to its DeviceSlot, or None when
    any segment is missing (DeviceTypeUtils.getDeviceSlotByPath:62-90:
    every segment but the last names a nested unit; the last names a
    slot of the unit reached)."""
    if schema is None:
        return None
    segments = [s for s in path.split("/") if s]
    if not segments:
        return None
    unit: DeviceUnit = schema
    for segment in segments[:-1]:
        unit = next((u for u in unit.device_units if u.path == segment),
                    None)
        if unit is None:
            return None
    return next((s for s in unit.device_slots
                 if s.path == segments[-1]), None)


@dataclass
class DeviceType(BrandedEntity):
    """Hardware/firmware class of devices (IDeviceType)."""

    container_policy: DeviceContainerPolicy = DeviceContainerPolicy.STANDALONE
    # For COMPOSITE types: the unit/slot tree child devices map into
    # (None for standalone types).
    device_element_schema: Optional[DeviceElementSchema] = None


class ParameterType(enum.Enum):
    """Command parameter wire types (spi/device/command/ParameterType.java,
    mirroring protobuf scalar types)."""

    DOUBLE = "Double"
    FLOAT = "Float"
    INT32 = "Int32"
    INT64 = "Int64"
    UINT32 = "UInt32"
    UINT64 = "UInt64"
    SINT32 = "SInt32"
    SINT64 = "SInt64"
    FIXED32 = "Fixed32"
    FIXED64 = "Fixed64"
    SFIXED32 = "SFixed32"
    SFIXED64 = "SFixed64"
    BOOL = "Bool"
    STRING = "String"
    BYTES = "Bytes"


@dataclass
class CommandParameter:
    """One parameter of a device command (ICommandParameter)."""

    name: str = ""
    type: ParameterType = ParameterType.STRING
    required: bool = False


@dataclass
class DeviceCommand(PersistentEntity):
    """Command callable on devices of a type (IDeviceCommand)."""

    device_type_id: str = ""
    namespace: str = ""
    name: str = ""
    description: str = ""
    parameters: List[CommandParameter] = field(default_factory=list)


@dataclass
class DeviceStatus(PersistentEntity):
    """Named device status within a type's state machine (IDeviceStatus)."""

    device_type_id: str = ""
    code: str = ""
    name: str = ""
    background_color: str = ""
    foreground_color: str = ""
    border_color: str = ""
    icon: str = ""


@dataclass
class DeviceElementMapping:
    """Composite-device slot -> child device mapping (IDeviceElementMapping)."""

    device_element_schema_path: str = ""
    device_token: str = ""


@dataclass
class Device(PersistentEntity):
    """Registered device (IDevice)."""

    device_type_id: str = ""
    parent_device_id: str = ""  # set when mapped into a composite parent
    status: str = ""  # code of a DeviceStatus
    comments: str = ""
    device_element_mappings: List[DeviceElementMapping] = field(default_factory=list)


class DeviceAssignmentStatus(enum.IntEnum):
    """Assignment state machine (spi/device/DeviceAssignmentStatus.java).

    Integer-valued: mirrored into the registry lookup tensor
    (registry/tensors.py) so validation runs on device.
    """

    ACTIVE = 1
    MISSING = 2
    RELEASED = 3


@dataclass
class DeviceAssignment(PersistentEntity):
    """Binding of a device to customer/area/asset for a period (IDeviceAssignment).

    Events are always recorded against an assignment, not a raw device.
    """

    device_id: str = ""
    device_type_id: str = ""
    customer_id: str = ""
    area_id: str = ""
    asset_id: str = ""
    status: DeviceAssignmentStatus = DeviceAssignmentStatus.ACTIVE
    active_date: Optional[int] = None
    released_date: Optional[int] = None


class DeviceGroupRole:
    """Well-known group element roles (reference uses free-form role strings)."""

    GROUP = "group"
    DEVICE = "device"


@dataclass
class DeviceGroup(BrandedEntity):
    """Named set of devices/groups with roles (IDeviceGroup)."""

    roles: List[str] = field(default_factory=list)


@dataclass
class DeviceGroupElement(PersistentEntity):
    """Member of a device group (IDeviceGroupElement): device OR nested group."""

    group_id: str = ""
    device_id: str = ""
    nested_group_id: str = ""
    roles: List[str] = field(default_factory=list)


class DeviceAlarmState(enum.Enum):
    """Alarm lifecycle (spi/device/DeviceAlarmState.java)."""

    TRIGGERED = "Triggered"
    ACKNOWLEDGED = "Acknowledged"
    RESOLVED = "Resolved"


@dataclass
class DeviceAlarm(PersistentEntity):
    """Persistent alarm on a device (IDeviceAlarm), raised by rule processors."""

    device_id: str = ""
    device_assignment_id: str = ""
    customer_id: str = ""
    area_id: str = ""
    asset_id: str = ""
    alarm_message: str = ""
    triggering_event_id: str = ""
    state: DeviceAlarmState = DeviceAlarmState.TRIGGERED
    triggered_date: Optional[int] = None
    acknowledged_date: Optional[int] = None
    resolved_date: Optional[int] = None


@dataclass
class DeviceStream(PersistentEntity):
    """Binary stream declared by a device under an assignment (IDeviceStream,
    reference: sitewhere-core-api spi/device/streaming/IDeviceStream.java).
    `token` holds the stream id; chunks are DeviceStreamData events."""

    assignment_id: str = ""
    content_type: str = "application/octet-stream"
