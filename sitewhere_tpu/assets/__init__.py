"""Asset management (reference: service-asset-management)."""

from sitewhere_tpu.assets.manager import AssetManagement

__all__ = ["AssetManagement"]
