"""Asset registry: people/hardware/locations bound to assignments.

Reference: service-asset-management — IAssetManagement CRUD over asset types
and assets (gRPC + Mongo/HBase persistence; the ~9k LoC of generated WSO2
SOAP stubs are a legacy identity-provider integration deliberately out of
scope — the extension point is the store-backed management API itself).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.asset import Asset, AssetType
from sitewhere_tpu.model.common import SearchCriteria, SearchResults, page
from sitewhere_tpu.registry.store import InMemoryStore, _Collection


class AssetManagement:
    """IAssetManagement for one tenant."""

    def __init__(self, store=None, tenant_id: str = "default"):
        store = store or InMemoryStore()
        self.tenant_id = tenant_id
        self.asset_types: _Collection[AssetType] = _Collection(
            "asset_type", AssetType, store, ErrorCode.INVALID_ASSET_TOKEN)
        self.assets: _Collection[Asset] = _Collection(
            "asset", Asset, store, ErrorCode.INVALID_ASSET_TOKEN)

    # -- asset types -------------------------------------------------------
    def create_asset_type(self, asset_type: AssetType) -> AssetType:
        return self.asset_types.create(asset_type)

    def get_asset_type_by_token(self, token: str) -> AssetType:
        return self.asset_types.require_by_token(token)

    def update_asset_type(self, token: str, updates: Dict) -> AssetType:
        entity = self.asset_types.require_by_token(token)
        return self.asset_types.update(entity.id, updates)

    def delete_asset_type(self, token: str) -> AssetType:
        entity = self.asset_types.require_by_token(token)
        in_use = [a for a in self.assets.all()
                  if a.asset_type_id == entity.id]
        if in_use:
            raise SiteWhereError(
                f"asset type '{token}' in use by {len(in_use)} assets")
        return self.asset_types.delete(entity.id)

    def list_asset_types(self, criteria: Optional[SearchCriteria] = None
                         ) -> SearchResults[AssetType]:
        return self.asset_types.list(criteria)

    # -- assets ------------------------------------------------------------
    def create_asset(self, asset: Asset) -> Asset:
        if asset.asset_type_id:
            self.asset_types.require(asset.asset_type_id)
        return self.assets.create(asset)

    def get_asset_by_token(self, token: str) -> Asset:
        return self.assets.require_by_token(token)

    def get_asset(self, asset_id: str) -> Optional[Asset]:
        return self.assets.get(asset_id)

    def update_asset(self, token: str, updates: Dict) -> Asset:
        entity = self.assets.require_by_token(token)
        return self.assets.update(entity.id, updates)

    def delete_asset(self, token: str) -> Asset:
        entity = self.assets.require_by_token(token)
        return self.assets.delete(entity.id)

    def list_assets(self, asset_type_token: Optional[str] = None,
                    criteria: Optional[SearchCriteria] = None
                    ) -> SearchResults[Asset]:
        items = self.assets.all()
        if asset_type_token:
            asset_type = self.asset_types.require_by_token(asset_type_token)
            items = [a for a in items if a.asset_type_id == asset_type.id]
        return page(items, criteria or SearchCriteria())
