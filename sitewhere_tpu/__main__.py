"""Command-line entrypoint: ``python -m sitewhere_tpu <command>``.

The reference ships each microservice as a runnable Spring Boot app
(sitewhere-microservice MicroserviceApplication.java:40 — process entry,
start() at :49); here the whole platform composes into one SPMD process,
so the CLI boots the single-process instance the same way an operator
would boot the reference's docker-compose stack.

Commands:

  serve    boot a SiteWhereInstance + REST gateway (+ optional networked
           bus edge for cross-process producers/consumers)
  openapi  print the generated OpenAPI 3 document and exit
  check    environment self-check: jax backend/devices, native runtime,
           virtual mesh availability
  version  print the package version

Configuration layers (runtime/config.py — the CLI uses the canonical
``DEFAULTS`` schema there): built-in defaults <- --config JSON file <-
SWTPU_* environment variables <- command-line flags. Example config file:

    {"instance": {"id": "prod"},
     "persist": {"data_dir": "/var/lib/swtpu"},
     "pipeline": {"enabled": true, "batch_size": 8192,
                  "max_devices": 131072},
     "mesh": {"shards": 8},
     "api": {"host": "0.0.0.0", "port": 8080},
     "bus": {"edge_port": 9092}}
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from typing import Optional


def _build_config(config_path: Optional[str]):
    from sitewhere_tpu.runtime.config import DEFAULTS, Configuration

    return Configuration(defaults=DEFAULTS, config_path=config_path)


def _build_instance(cfg, mesh=None):
    from sitewhere_tpu.instance import SiteWhereInstance

    mode = cfg.get("pipeline.mode") or "throughput"
    if mode not in ("throughput", "latency"):
        raise SystemExit(f"pipeline.mode must be 'throughput' or 'latency',"
                         f" got {mode!r}")
    # latency mode: the engine's compiled batch shape IS the latency
    # lever (pack + H2D + step scale with it); ingest then flushes
    # adaptively (pipeline/feed.py AdaptiveBatcher semantics)
    batch_size = int(cfg.get("pipeline.latency_batch_size")
                     if mode == "latency"
                     else cfg.get("pipeline.batch_size"))
    # boot-armed fault plan (runtime/faults.py): only built when rules
    # are declared, so the default config boots with injection disarmed
    fault_rules = cfg.get("faults.rules") or []
    fault_plan = ({"seed": int(cfg.get("faults.seed") or 0),
                   "rules": [dict(r) for r in fault_rules]}
                  if fault_rules else None)
    return SiteWhereInstance(
        mesh=mesh,
        instance_id=cfg.get("instance.id"),
        data_dir=cfg.get("persist.data_dir"),
        enable_pipeline=bool(cfg.get("pipeline.enabled")),
        max_devices=int(cfg.get("pipeline.max_devices")),
        max_zones=int(cfg.get("pipeline.max_zones")),
        max_zone_vertices=int(cfg.get("pipeline.max_zone_vertices")),
        batch_size=batch_size,
        measurement_slots=int(cfg.get("pipeline.measurement_slots")),
        max_tenants=int(cfg.get("pipeline.max_tenants")),
        bus_partitions=int(cfg.get("bus.partitions")),
        default_tenant=cfg.get("instance.default_tenant"),
        admin_username=cfg.get("instance.admin_username"),
        admin_password=cfg.get("instance.admin_password"),
        shards=int(cfg.get("mesh.shards")),
        # "auto" -> None: the engine decides by mesh shape/topology
        device_routing={"on": True, "off": False}.get(
            str(cfg.get("pipeline.device_routing") or "auto").lower()),
        h2d_buffer_depth=int(cfg.get("pipeline.h2d_buffer_depth") or 3),
        checkpoint_interval_s=(
            float(cfg.get("persist.checkpoint_interval_s"))
            if cfg.get("persist.checkpoint_interval_s") is not None
            else None),
        latency_linger_ms=(float(cfg.get("pipeline.linger_ms"))
                           if mode == "latency" else None),
        latency_adaptive=bool(cfg.get("pipeline.adaptive_linger")),
        allow_fault_drills=bool(cfg.get("faults.allow_drills")),
        fault_plan=fault_plan,
        admission_step_budget_ms=(
            float(cfg.get("faults.admission_step_budget_ms"))
            if cfg.get("faults.admission_step_budget_ms") is not None
            else None),
        admission_queue_depth_budget=(
            int(cfg.get("faults.admission_queue_depth_budget"))
            if cfg.get("faults.admission_queue_depth_budget") is not None
            else None),
        trace_sample_n=int(cfg.get("observability.trace_sample_n") or 0),
        serving_workers=int(cfg.get("serving.workers") or 4),
        serving_queue_depth_budget=int(
            cfg.get("serving.queue_depth_budget") or 64),
        serving_latency_budget_ms=float(
            cfg.get("serving.latency_budget_ms") or 0.0),
        serving_cache_mb=float(cfg.get("serving.cache_mb") or 64.0),
        serving_mesh_row_threshold=(
            int(cfg.get("serving.mesh_row_threshold"))
            if cfg.get("serving.mesh_row_threshold") is not None
            else None),
        refit_interval_s=(
            float(cfg.get("actuation.refit_interval_s"))
            if cfg.get("actuation.refit_interval_s") else None))


def _apply_rule_config(instance, cfg) -> None:
    """Install the config-declared fused rules on the booted engine (the
    reference's RuleProcessingParser spring wiring of
    ZoneTestRuleProcessor; the metamodel element is
    runtime/config_model.py rule_processing_model)."""
    rules = cfg.get("rules") or []
    engine = instance.pipeline_engine
    if engine is None:
        if rules:
            print("warning: config declares rules but the pipeline is "
                  "disabled; ignoring", file=sys.stderr)
        return
    from sitewhere_tpu.pipeline.engine import rule_from_dict

    for data in rules:
        if data.get("type") == "scripted":
            _apply_scripted_rule(instance, dict(data))
            continue
        kind, rule = rule_from_dict(dict(data))
        # upsert: config wins over a restored checkpoint's copy of the
        # same token (restore_on_boot runs inside instance.start(),
        # BEFORE this) without duplicating it
        engine.upsert_rule(kind, rule)


def _apply_scripted_rule(instance, data: dict) -> None:
    """Install a config-declared script-backed rule processor on a tenant
    engine (the reference's Groovy ZoneTest-style processors, spring-wired
    there; declared in the same `rules` config list here). Goes through
    the instance's durable install path, so a config-declared rule is
    indistinguishable from a REST-installed one (replicated, restored at
    boot)."""
    from sitewhere_tpu.errors import SiteWhereError

    token = data.get("token") or ""
    script_id = data.get("script") or ""
    if not token or not script_id:
        raise SiteWhereError("scripted rules require 'token' and 'script'")
    tenant = data.get("tenant") or instance._default_tenant or "default"
    engine = instance.get_tenant_engine(tenant)
    if engine is None:
        raise SiteWhereError(f"scripted rule {token!r}: unknown tenant "
                             f"{tenant!r}")
    existing = engine.rule_processors.get_processor(token)
    if existing is not None and getattr(existing, "script_id",
                                        None) == script_id:
        return  # idempotent reboot (boot restore already installed it)
    # config declares desired state: replace whatever is installed
    instance.install_scripted_rule(tenant, token, script_id, replace=True)


def _apply_search_config(instance, cfg) -> None:
    """Register config-declared EXTERNAL search providers on tenant
    engines (the reference's Spring-wired SolrSearchProvider slot;
    metamodel element: runtime/config_model.py event_search_model)."""
    providers = cfg.get("search_providers") or []
    if not providers:
        return
    from sitewhere_tpu.search import HttpSearchProvider

    for data in providers:
        if data.get("type") != "http":
            print(f"warning: unknown search provider type "
                  f"{data.get('type')!r}; skipping", file=sys.stderr)
            continue
        tenant = data.get("tenant") or instance._default_tenant or "default"
        engine = instance.get_tenant_engine(tenant)
        if engine is None:
            print(f"warning: search provider "
                  f"{data.get('provider_id')!r} names unknown tenant "
                  f"{tenant!r}; skipping", file=sys.stderr)
            continue
        engine.search_providers.register(HttpSearchProvider(
            data["provider_id"], data["base_url"],
            name=data.get("name", ""),
            timeout_s=float(data.get("timeout_s", 10.0))))


def cmd_assemble_checkpoint(args) -> int:
    """Merge one per-host shard checkpoint from every cluster host into a
    canonical checkpoint that restores onto any topology (other host
    counts, shard counts, or a single chip)."""
    from sitewhere_tpu.persist.checkpoint import write_assembled

    path = write_assembled(list(args.sources), args.out)
    print(path)
    return 0


def _install_stop_handlers(stop: Optional[threading.Event] = None
                           ) -> threading.Event:
    """SIGINT/SIGTERM set `stop` for a graceful serve-loop exit; the
    handler then restores the DEFAULT disposition, so a second signal
    force-exits — a boot hung inside a blocking call (unreachable
    cluster coordinator, stuck replay) stays killable with a repeated
    Ctrl+C / SIGTERM. Re-call with the same event after
    jax.distributed.initialize, which installs its own handlers over
    ours."""
    stop = stop or threading.Event()

    def _sig(signum, _frame):
        stop.set()
        signal.signal(signum, signal.SIG_DFL)

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    return stop


def _parse_peers(spec: Optional[str]) -> dict:
    """'0=hostA:9092,1=hostB:9092' -> {0: ("hostA", 9092), ...}."""
    out = {}
    if not spec:
        return out
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        pid, _, addr = part.partition("=")
        host, _, port = addr.rpartition(":")
        out[int(pid)] = (host, int(port))
    return out


def _serve_feeder(cfg) -> int:
    """Run ONE feeder worker process (``serve --feeder``): no instance,
    no engine — connect to the mesh host's bus edge, lease source
    partitions, and run decode -> intern -> pack -> guard -> ship until
    stopped. Composes with --supervise (generic argv passthrough): a
    killed feeder restarts with a freshly minted epoch, above any floor
    its previous incarnation was fenced at."""
    import os
    import socket

    from sitewhere_tpu.feeders import FeederWorker
    from sitewhere_tpu.runtime.recovery import mint_epoch

    connect = cfg.get("feeders.connect")
    if not connect:
        print("serve --feeder requires --feeder-connect host:port "
              "(the mesh host's bus edge)", file=sys.stderr)
        return 2
    host, _, port = str(connect).rpartition(":")
    name = cfg.get("feeders.name") or f"{socket.gethostname()}:{os.getpid()}"
    spec = cfg.get("feeders.partitions")
    partitions = None
    if spec not in (None, ""):
        partitions = [int(p) for p in str(spec).split(",") if p.strip()]
    epoch = mint_epoch(cfg.get("persist.data_dir"))
    stop = _install_stop_handlers()
    worker = FeederWorker(
        host or "127.0.0.1", int(port), name, epoch=epoch,
        partitions=partitions,
        poll_max_records=int(cfg.get("feeders.poll_max_records")),
        shed_backoff_s=float(cfg.get("feeders.shed_backoff_s")),
        hard_exit=True)
    hello = worker.connect()
    worker.acquire_leases()
    print(f"sitewhere-tpu feeder '{name}' serving", flush=True)
    print(f"  mesh host  : tcp://{connect}", flush=True)
    print(f"  topic      : {hello['topic']} "
          f"({hello['partitions']} partitions)", flush=True)
    print(f"  epoch      : {epoch}", flush=True)
    print(f"  partitions : {sorted(worker.owned) or '(contending)'}",
          flush=True)
    try:
        while not stop.is_set():
            if worker.run_once() == 0 and not worker.owned:
                # nothing leased yet (another worker holds everything):
                # retry acquisition on a lazy cadence instead of spinning
                stop.wait(0.5)
    finally:
        worker.stop()
    return 0


def cmd_serve(args) -> int:
    from sitewhere_tpu.runtime.busnet import BusServer
    from sitewhere_tpu.web.server import RestServer

    cfg = _build_config(args.config)
    # flags override file/env layers
    if args.data_dir is not None:
        cfg.set("persist.data_dir", args.data_dir)
    if args.port is not None:
        cfg.set("api.port", args.port)
    if args.host is not None:
        cfg.set("api.host", args.host)
    if args.shards is not None:
        cfg.set("mesh.shards", args.shards)
    if args.no_pipeline:
        cfg.set("pipeline.enabled", False)
    if args.bus_port is not None:
        cfg.set("bus.edge_port", args.bus_port)
    for flag, key in (("feeder_connect", "feeders.connect"),
                      ("feeder_name", "feeders.name"),
                      ("feeder_partitions", "feeders.partitions")):
        value = getattr(args, flag, None)
        if value is not None:
            cfg.set(key, value)
    if getattr(args, "feeder", False):
        return _serve_feeder(cfg)
    if getattr(args, "feeders", False):
        cfg.set("feeders.enabled", True)
    for flag, key in (("cluster_coordinator", "cluster.coordinator"),
                      ("cluster_num_processes", "cluster.num_processes"),
                      ("cluster_process_id", "cluster.process_id"),
                      ("cluster_peers", "cluster.peers")):
        value = getattr(args, flag, None)
        if value is not None:
            cfg.set(key, value)

    coordinator = cfg.get("cluster.coordinator")
    if coordinator:
        return _serve_cluster(cfg)
    if cfg.get("cluster.peers") and cfg.get("cluster.process_id") is not None:
        # peers without a coordinator: control-plane-only cluster — N
        # independent single-host instances whose registries + tenant/
        # user provisioning converge over busnet (no jax.distributed
        # gang; parallel/cluster.py ControlPlaneCluster)
        return _serve_control_plane(cfg)

    # graceful-shutdown handlers BEFORE the (slow) boot: a SIGTERM that
    # lands mid-boot or in the window right after the serving banner must
    # stop the loop and exit 0, never die on the default handler
    stop = _install_stop_handlers()

    instance = _build_instance(cfg)
    instance.start()
    _apply_rule_config(instance, cfg)
    _apply_search_config(instance, cfg)
    # opt-in usage telemetry (the MicroserviceAnalytics role; OFF unless
    # telemetry.enabled + telemetry.endpoint are configured)
    from sitewhere_tpu.runtime.telemetry import build_from_config
    telemetry = build_from_config(cfg, instance.instance_id)
    if telemetry is not None:
        telemetry.start()
    rest = RestServer(instance, host=cfg.get("api.host"),
                      port=int(cfg.get("api.port")),
                      token_expiration_minutes=int(
                          cfg.get("api.jwt_expiration_min")))
    rest.start()
    bus_server = None
    edge_port = cfg.get("bus.edge_port")
    if edge_port is not None:
        bus_server = BusServer(instance.bus, host=cfg.get("api.host"),
                               port=int(edge_port))
        bus_server.start()
    feeder_service = None
    if (cfg.get("feeders.enabled") and bus_server is not None
            and instance.pipeline_engine is not None):
        # mount the feeder fleet's landing zone on the bus edge: remote
        # workers lease partitions of the frames topic and this host's
        # per-step work on their blobs shrinks to H2D + step
        from sitewhere_tpu.feeders import FeederService
        from sitewhere_tpu.sources.manager import GLOBAL_ADMISSION
        feeder_service = FeederService(
            instance.pipeline_engine, bus_server,
            frames_topic=(cfg.get("feeders.frames_topic")
                          or instance.naming.feeder_frames()),
            lease_ttl_s=float(cfg.get("feeders.lease_ttl_s")),
            tenant=cfg.get("instance.default_tenant") or "default",
            admission=GLOBAL_ADMISSION)

    print(f"sitewhere-tpu instance '{instance.instance_id}' serving",
          flush=True)
    print(f"  REST gateway : {rest.base_url}", flush=True)
    print(f"  OpenAPI doc  : {rest.base_url}/api/openapi.json", flush=True)
    if bus_server is not None:
        print(f"  bus edge     : tcp://{cfg.get('api.host')}:"
              f"{bus_server.port}", flush=True)
    if feeder_service is not None:
        print(f"  feeder fleet : topic {feeder_service.frames_topic} "
              f"(lease ttl {feeder_service.lease_ttl_s:g}s)", flush=True)

    try:
        while not stop.wait(1.0):
            pass
    finally:
        if bus_server is not None:
            bus_server.stop()
        rest.stop()
        instance.stop()
        if telemetry is not None:
            telemetry.stop()
    return 0


def _serve_control_plane(cfg) -> int:
    """Boot one host of a control-plane-replicated deployment: a plain
    single-host instance (own local pipeline) plus the busnet edge and
    the replication stack — registry gossip, tenant/user provisioning
    with reactive engine lifecycle, script replication, heartbeats.
    REST mutations on any host converge everywhere without restarts; a
    killed host restarts alone (wrap with --supervise) and rebuilds its
    tenant set from checkpoint + durable stores, not templates."""
    from sitewhere_tpu.parallel.cluster import ControlPlaneCluster
    from sitewhere_tpu.web.server import RestServer

    stop = _install_stop_handlers()
    process_id = int(cfg.get("cluster.process_id"))
    num_processes = int(cfg.get("cluster.num_processes") or 0) or \
        (len(_parse_peers(cfg.get("cluster.peers"))) or 1)
    instance = _build_instance(cfg)
    peers = _parse_peers(cfg.get("cluster.peers"))
    edge_port = cfg.get("bus.edge_port")
    cluster = ControlPlaneCluster(
        instance, process_id, num_processes,
        peer_bus_addrs=peers,
        bus_host=cfg.get("api.host"),
        bus_port=int(edge_port) if edge_port is not None else 0,
        heartbeat_s=float(cfg.get("cluster.heartbeat_s")),
        stale_after_s=float(cfg.get("cluster.stale_after_s")))
    cluster.start()
    _apply_rule_config(instance, cfg)
    _apply_search_config(instance, cfg)
    from sitewhere_tpu.runtime.telemetry import build_from_config
    telemetry = build_from_config(cfg, instance.instance_id)
    if telemetry is not None:
        telemetry.start()
    rest = RestServer(instance, host=cfg.get("api.host"),
                      port=int(cfg.get("api.port")),
                      token_expiration_minutes=int(
                          cfg.get("api.jwt_expiration_min")))
    rest.start()

    print(f"sitewhere-tpu control-plane host {process_id}/{num_processes} "
          f"instance '{instance.instance_id}' serving", flush=True)
    print(f"  REST gateway : {rest.base_url}", flush=True)
    print(f"  bus edge     : tcp://{cfg.get('api.host')}:"
          f"{cluster.bus_port}", flush=True)

    _install_stop_handlers(stop)
    try:
        while not stop.wait(1.0):
            pass
    finally:
        rest.stop()
        cluster.stop()
        if telemetry is not None:
            telemetry.stop()
    return 0


def _serve_cluster(cfg) -> int:
    """Boot one host of an N-process instance: join the jax.distributed
    cluster, build the instance over the GLOBAL mesh, and compose the
    cluster services (lockstep step loop, busnet edge, foreign-row
    forwarding, heartbeats/topology, peer watchdog) around it
    (parallel/cluster.py; reference boot: Microservice.java:182-236)."""
    from sitewhere_tpu.parallel.cluster import ClusterService
    from sitewhere_tpu.parallel.distributed import (
        initialize, make_global_mesh)
    from sitewhere_tpu.web.server import RestServer

    # handlers before the (very slow) distributed boot — see cmd_serve
    stop = _install_stop_handlers()

    process_id = int(cfg.get("cluster.process_id"))
    num_processes = int(cfg.get("cluster.num_processes"))
    initialize(coordinator_address=cfg.get("cluster.coordinator"),
               num_processes=num_processes, process_id=process_id)
    # jax.distributed.initialize installs its own signal handling:
    # re-assert ours immediately so a SIGTERM during the rest of the
    # (slow) boot still reaches the stop event
    _install_stop_handlers(stop)
    mesh = make_global_mesh()
    instance = _build_instance(cfg, mesh=mesh)
    peers = _parse_peers(cfg.get("cluster.peers"))
    edge_port = cfg.get("bus.edge_port")
    cluster = ClusterService(
        instance, process_id, num_processes,
        peer_bus_addrs=peers,
        bus_host=cfg.get("api.host"),
        bus_port=int(edge_port) if edge_port is not None else 0,
        heartbeat_s=float(cfg.get("cluster.heartbeat_s")),
        stale_after_s=float(cfg.get("cluster.stale_after_s")),
        fail_after_s=float(cfg.get("cluster.fail_after_s")),
        presence_every_ticks=int(cfg.get("cluster.presence_every_ticks")),
        exit_on_peer_loss=bool(cfg.get("cluster.exit_on_peer_loss")),
        peer_loss_exit_code=int(cfg.get("cluster.peer_loss_exit_code")),
        registry_gossip=bool(cfg.get("cluster.registry_gossip")))
    cluster.start()
    # config rules install AFTER cluster.start (the gossip hook is live,
    # but every host boots the same config, so applies are idempotent
    # replace-on-add at the peers)
    _apply_rule_config(instance, cfg)
    _apply_search_config(instance, cfg)
    from sitewhere_tpu.runtime.telemetry import build_from_config
    telemetry = build_from_config(cfg, instance.instance_id)
    if telemetry is not None:
        telemetry.start()
    rest = RestServer(instance, host=cfg.get("api.host"),
                      port=int(cfg.get("api.port")),
                      token_expiration_minutes=int(
                          cfg.get("api.jwt_expiration_min")))
    rest.start()

    print(f"sitewhere-tpu cluster host {process_id}/{num_processes} "
          f"instance '{instance.instance_id}' serving")
    print(f"  REST gateway : {rest.base_url}")
    print(f"  bus edge     : tcp://{cfg.get('api.host')}:{cluster.bus_port}")
    print(f"  mesh         : {mesh.devices.size} shards over "
          f"{num_processes} hosts", flush=True)

    # belt-and-braces: nothing later in boot is known to stomp the
    # handlers, but re-asserting next to the serve loop keeps the
    # shutdown contract local and obvious
    _install_stop_handlers(stop)
    try:
        while not stop.wait(1.0):
            if cluster.loop.fatal is not None:
                return 1
    finally:
        rest.stop()
        cluster.stop()
        if telemetry is not None:
            telemetry.stop()
    return 0


def cmd_openapi(args) -> int:
    from sitewhere_tpu.web.openapi import generate_openapi
    from sitewhere_tpu.web.server import RestServer

    import sitewhere_tpu

    cfg = _build_config(args.config)
    # Doc generation needs only the router: no device engine, and no
    # durable state — a data_dir would replay bus segments and open
    # append handles on files a live `serve` process may be writing.
    cfg.set("pipeline.enabled", False)
    cfg.set("persist.data_dir", None)
    instance = _build_instance(cfg)
    rest = RestServer(instance)  # builds the router; not started
    doc = generate_openapi(rest.router, version=sitewhere_tpu.__version__)
    json.dump(doc, sys.stdout, indent=2)
    print()
    return 0


def cmd_check(_args) -> int:
    import sitewhere_tpu
    from sitewhere_tpu import native

    print(f"sitewhere-tpu {sitewhere_tpu.__version__}")
    ok = True
    if native.available():
        print("native host runtime: ok (libswt_host.so)")
    else:
        # pure-Python fallback is a supported mode, not a failure
        print(f"native host runtime: fallback ({native.build_error()})")
    try:
        import jax

        devs = jax.devices()
        print(f"jax backend: {devs[0].platform} x{len(devs)} "
              f"({devs[0].device_kind})")
        cpus = jax.devices("cpu")
        print(f"cpu mesh devices: {len(cpus)} "
              "(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
              "for virtual shards)")
    except Exception as exc:  # noqa: BLE001 - report, don't crash the check
        ok = False
        print(f"jax: FAILED ({exc})")
    return 0 if ok else 1


def cmd_version(_args) -> int:
    import sitewhere_tpu

    print(sitewhere_tpu.__version__)
    return 0


def cmd_deadletters(args) -> int:
    """Operator loop over parked records on a RUNNING instance (REST):
    list backlogs, inspect records, replay into the reprocess pipeline
    (runtime/deadletter.py; reference: inbound-reprocess-events,
    KafkaTopicNaming.java:48-69)."""
    from sitewhere_tpu.client.rest import SiteWhereClient

    client = SiteWhereClient(args.url)
    client.authenticate(args.username, args.password)
    if args.action == "list":
        topics = client.get("/api/instance/deadletters")["topics"]
        if not topics:
            print("no parked records")
            return 0
        for t in topics:
            print(f"{t['topic']}\n  records={t['records']} "
                  f"backlog={t['replayBacklog']} -> {t['replayTarget']}")
        return 0
    if not args.topic:
        print("error: --topic required for this action", file=sys.stderr)
        return 2
    if args.action == "show":
        out = client.get("/api/instance/deadletters/records",
                         topic=args.topic, limit=args.limit)
        for r in out["records"]:
            print(f"[{r['partition']}:{r['offset']}] key={r['key']} "
                  f"{r['size']}B {json.dumps(r['preview'])}")
        if not out["records"]:
            print("(no records behind the replay cursor)")
        return 0
    if args.action == "replay":
        body = {"topic": args.topic, "max": args.limit}
        if args.target:
            body["target"] = args.target
        out = client.post("/api/instance/deadletters/replay", body)
        print(f"replayed {out['replayed']} -> {out['target']} "
              f"(remaining {out['remaining']})")
        return 0
    print(f"unknown action {args.action}", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sitewhere_tpu",
        description="TPU-native IoT application enablement platform")
    sub = parser.add_subparsers(dest="command", required=True)

    serve = sub.add_parser("serve", help="boot instance + REST gateway")
    serve.add_argument("--supervise", action="store_true",
                       help="wrap serve in a gang-restart supervisor: an "
                            "abnormal exit (peer loss, crash) restarts "
                            "the process; exit 0 ends supervision")
    serve.add_argument("--supervise-backoff", type=float, default=1.0,
                       help="seconds between restarts (default 1.0)")
    serve.add_argument("--config", help="JSON config file (layered)")
    serve.add_argument("--data-dir", help="durable state directory")
    serve.add_argument("--host", help="bind host (default 127.0.0.1)")
    serve.add_argument("--port", type=int, help="REST port (default 8080)")
    serve.add_argument("--shards", type=int,
                       help="device-mesh shards for the pipeline engine")
    serve.add_argument("--no-pipeline", action="store_true",
                       help="control plane only (no device engine)")
    serve.add_argument("--bus-port", type=int,
                       help="expose the event bus on TCP for edge processes")
    serve.add_argument("--feeders", action="store_true",
                       help="mesh host: mount the feeder-fleet landing "
                            "zone on the bus edge (feeders.enabled; "
                            "requires --bus-port)")
    serve.add_argument("--feeder", action="store_true",
                       help="run as a FEEDER WORKER process instead of "
                            "an instance: lease source partitions on the "
                            "mesh host named by --feeder-connect and "
                            "ship packed wire blobs (docs/FEEDERS.md)")
    serve.add_argument("--feeder-connect",
                       help="feeder mode: mesh host bus edge host:port")
    serve.add_argument("--feeder-name",
                       help="feeder lease identity (default host:pid)")
    serve.add_argument("--feeder-partitions",
                       help="feeder mode: csv partition pin, e.g. '0,1' "
                            "(default: contend for every partition)")
    serve.add_argument("--cluster-coordinator",
                       help="jax.distributed coordinator host:port — "
                            "enables multi-host cluster mode")
    serve.add_argument("--cluster-num-processes", type=int,
                       help="total processes in the cluster")
    serve.add_argument("--cluster-process-id", type=int,
                       help="this process's id (0..N-1)")
    serve.add_argument("--cluster-peers",
                       help="peer bus edges: '0=hostA:9092,1=hostB:9092'")
    serve.set_defaults(fn=cmd_serve)

    openapi = sub.add_parser("openapi", help="print the OpenAPI document")
    openapi.add_argument("--config", help="JSON config file")
    openapi.set_defaults(fn=cmd_openapi)

    check = sub.add_parser("check", help="environment self-check")
    check.set_defaults(fn=cmd_check)

    version = sub.add_parser("version", help="print version")
    version.set_defaults(fn=cmd_version)

    assemble = sub.add_parser(
        "assemble-checkpoint",
        help="merge per-host cluster checkpoints into one canonical "
             "checkpoint restorable on ANY topology")
    assemble.add_argument("sources", nargs="+",
                          help="one ckpt-* directory per cluster host")
    assemble.add_argument("--out", required=True,
                          help="checkpoint directory to write into "
                               "(e.g. <data_dir>/checkpoints)")
    assemble.set_defaults(fn=cmd_assemble_checkpoint)

    dl = sub.add_parser("deadletters",
                        help="list/inspect/replay parked records on a "
                             "running instance")
    dl.add_argument("action", choices=["list", "show", "replay"])
    dl.add_argument("--url", default="http://127.0.0.1:8080",
                    help="REST gateway base URL")
    dl.add_argument("--username", default="admin")
    dl.add_argument("--password", default="password")
    dl.add_argument("--topic", help="parked topic (show/replay)")
    dl.add_argument("--target",
                    help="replay destination (default: the reprocess "
                         "topic for decoded events, else the base topic)")
    dl.add_argument("--limit", type=int, default=100,
                    help="records to show / max to replay")
    dl.set_defaults(fn=cmd_deadletters)

    args = parser.parse_args(argv)
    if getattr(args, "supervise", False):
        # re-exec serve (without --supervise) under the gang-restart
        # supervisor (runtime/supervisor.py; the reference's zero-operator
        # recovery analog, MicroserviceKafkaConsumer.java:88 rebalance)
        from sitewhere_tpu.runtime.supervisor import supervise_serve

        raw = list(sys.argv[1:] if argv is None else argv)
        child_argv = []
        skip = False
        for item in raw:
            if skip:
                skip = False
                continue
            if item == "--supervise":
                continue
            if item == "--supervise-backoff":
                skip = True
                continue
            if item.startswith("--supervise-backoff="):
                continue
            child_argv.append(item)
        return supervise_serve(child_argv,
                               backoff_s=args.supervise_backoff)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
