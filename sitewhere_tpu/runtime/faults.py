"""Deterministic fault injection: seeded, schedule-driven fault plans.

TensorFlow (Abadi et al. 2016) treats checkpoint recovery as a
continuously-exercised property, and tf.data service assumes input
workers die routinely — this module gives the runtime the same
discipline. A :class:`FaultPlan` is a seeded schedule over *named fault
points* threaded through the hot path and control plane:

  pack_fail             host pack (batch -> wire blob)
  h2d_error             host -> device staging transfer
  dispatch_error        jitted step dispatch
  lane_fetch_error      the single alert-lane D2H fetch
  busnet_drop           bus server eats a response (lost-reply)
  busnet_delay          bus server stalls before replying
  busnet_partition      bus server refuses every op for a window
  checkpoint_torn_write checkpoint dir renamed with truncated state
  feeder_thread_death   pipelined-feeder stager thread dies
  rest_worker_stall     REST worker thread stalls mid-request

Disarmed cost is pinned by perf_gate's ``fault_injection_overhead``
check (same pattern as ``observability_overhead``): :func:`fault_point`
compiles down to one module-global load and an identity test — no dict
lookup, no allocation, no lock — when no plan is armed.

Determinism: each fault point draws from its own ``random.Random``
stream keyed (seed, point), and fires are further gated by exact
``after`` / ``times`` hit windows, so a drill's schedule replays
bit-for-bit from its seed regardless of thread interleaving elsewhere.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS

FAULT_POINTS = (
    "pack_fail",
    "h2d_error",
    "dispatch_error",
    "lane_fetch_error",
    "busnet_drop",
    "busnet_delay",
    "busnet_partition",
    "checkpoint_torn_write",
    "feeder_thread_death",
    "feeder_process_death",
    "rest_worker_stall",
    "command_delivery_error",
)

# points whose firing is an *error* raised into the caller (the rest are
# directives the call site interprets: delays, drops, windows)
_RAISING_POINTS = frozenset((
    "pack_fail", "h2d_error", "dispatch_error", "lane_fetch_error",
    "checkpoint_torn_write", "feeder_thread_death",
    # feeder_process_death extends the thread-death drill to feeder
    # PROCESSES: fired mid-blob in the feeder worker's ship loop, the
    # worker dies WITHOUT committing or releasing its lease (os._exit in
    # `serve --feeder`; abandoned thread in the in-proc drill) — the
    # takeover path, not the error path, must recover it.
    "feeder_process_death",
    # raised into CommandFanout's per-fire delivery attempt: the fan-out
    # retries in line, then parks the fire on the dead-letter list — the
    # drill asserts delivered + parked == lane rows (conservation)
    "command_delivery_error",
))


class FaultError(RuntimeError):
    """An injected fault. Distinct from organic errors so drills can
    assert the failure they observed is the one they scheduled."""

    def __init__(self, point: str):
        super().__init__(f"injected fault: {point}")
        self.point = point


class FaultRule:
    """One schedule entry: fire `point` with probability `p` on each hit,
    skipping the first `after` hits, at most `times` fires total.
    `delay_s` is the stall for delay-mode points; `duration_s` opens a
    window (busnet_partition) instead of firing per-hit."""

    __slots__ = ("point", "p", "times", "after", "delay_s", "duration_s",
                 "hits", "fires", "window_until", "_rng")

    def __init__(self, point: str, p: float = 1.0,
                 times: Optional[int] = None, after: int = 0,
                 delay_s: float = 0.0, duration_s: float = 0.0):
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point '{point}' "
                             f"(known: {', '.join(FAULT_POINTS)})")
        self.point = point
        self.p = float(p)
        self.times = None if times is None else int(times)
        self.after = int(after)
        self.delay_s = float(delay_s)
        self.duration_s = float(duration_s)
        self.hits = 0
        self.fires = 0
        self.window_until = 0.0
        self._rng: Optional[random.Random] = None

    def bind(self, seed: int) -> None:
        # per-point stream: concurrent draws at OTHER points never
        # perturb this point's schedule
        self._rng = random.Random(f"{seed}:{self.point}")

    def should_fire(self) -> bool:
        self.hits += 1
        if self.times is not None and self.fires >= self.times:
            return False
        if self.hits <= self.after:
            return False
        if self.p < 1.0:
            rng = self._rng or random.Random(self.point)
            if rng.random() >= self.p:
                return False
        self.fires += 1
        return True

    def to_json(self) -> Dict:
        return {"point": self.point, "p": self.p, "times": self.times,
                "after": self.after, "delay_s": self.delay_s,
                "duration_s": self.duration_s,
                "hits": self.hits, "fires": self.fires}


class FaultPlan:
    """A seeded set of :class:`FaultRule` entries, armed process-wide via
    :func:`arm`. Thread-safe: rule bookkeeping is tiny and guarded by one
    lock only on the armed (drill) path — the disarmed path never enters
    this class."""

    def __init__(self, seed: int = 0,
                 rules: Optional[List[FaultRule]] = None):
        self.seed = int(seed)
        self._rules: Dict[str, List[FaultRule]] = {}
        self._lock = threading.Lock()
        for rule in rules or []:
            self.add(rule)

    @classmethod
    def from_json(cls, doc: Dict) -> "FaultPlan":
        rules = []
        for r in doc.get("rules", []):
            rules.append(FaultRule(
                r["point"], p=r.get("p", 1.0), times=r.get("times"),
                after=r.get("after", 0), delay_s=r.get("delay_s", 0.0),
                duration_s=r.get("duration_s", 0.0)))
        return cls(seed=doc.get("seed", 0), rules=rules)

    def add(self, rule: FaultRule) -> None:
        rule.bind(self.seed)
        self._rules.setdefault(rule.point, []).append(rule)

    def check(self, point: str) -> Optional[FaultRule]:
        """The armed-path half of :func:`fault_point`: returns the rule
        that fired (None otherwise). Window-mode rules report fired for
        the whole open window."""
        rules = self._rules.get(point)
        if not rules:
            return None
        with self._lock:
            now = time.monotonic()
            for rule in rules:
                if rule.duration_s > 0.0:
                    if now < rule.window_until:
                        return rule
                    if rule.should_fire():
                        rule.window_until = now + rule.duration_s
                        return rule
                elif rule.should_fire():
                    return rule
        return None

    def report(self) -> Dict:
        with self._lock:
            return {"seed": self.seed,
                    "rules": [r.to_json()
                              for rs in self._rules.values() for r in rs]}


# Process-wide armed plan. None (the common case) keeps fault_point a
# two-instruction no-op; drills swap in a plan via arm()/disarm().
_ACTIVE: Optional[FaultPlan] = None
_INJECTED = GLOBAL_METRICS.counter("faults.injected")


def arm(plan: FaultPlan) -> None:
    global _ACTIVE
    _ACTIVE = plan


def disarm() -> None:
    global _ACTIVE
    _ACTIVE = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def fault_point(point: str) -> Optional[FaultRule]:
    """Hot-path hook. Disarmed: one global load + identity test, nothing
    else (pinned < 0.5% of step wall by perf_gate). Armed: raising points
    raise :class:`FaultError`; delay-mode points sleep `delay_s` then
    return; directive points (busnet_drop/partition) return the fired
    rule for the call site to interpret."""
    plan = _ACTIVE
    if plan is None:
        return None
    rule = plan.check(point)
    if rule is None:
        return None
    _INJECTED.inc()
    # per-point counters are computed names; the `faults.point.` prefix
    # convention is documented in docs/OBSERVABILITY.md prose
    GLOBAL_METRICS.counter(f"faults.point.{point}").inc()
    if rule.delay_s > 0.0 and point not in _RAISING_POINTS:
        time.sleep(rule.delay_s)
        return rule
    if point in _RAISING_POINTS:
        raise FaultError(point)
    return rule
