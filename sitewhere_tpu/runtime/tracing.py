"""Lightweight distributed-tracing spans.

Reference: OpenTracing + Jaeger spans around lifecycle ops and gRPC calls
(sitewhere-grpc-model tracing/ServerTracingInterceptor.java,
TracerUtils.java:17-37). Here: in-proc span tree with a ring-buffer exporter
that the REST API can dump; `jax.profiler` traces cover the on-device side
(pipeline exposes start_device_trace/stop_device_trace).

Cross-thread parentage: the active-span stack is thread-local, so a span
opened on a feeder thread cannot see its logical parent on the submit
thread.  `TraceContext` carries (trace_id, span_id) explicitly across the
hop — `Tracer.span(..., parent=ctx)` overrides the stack lookup, and
`extract_traceparent`/`inject_traceparent` map the same context to the
W3C `traceparent` header for REST ingress/egress.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional


@dataclass(frozen=True)
class TraceContext:
    """Explicit parent handoff across thread hops and the wire."""
    trace_id: str
    span_id: str


_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def extract_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """W3C `traceparent` header -> TraceContext (None if absent/invalid)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id=trace_id, span_id=span_id)


def inject_traceparent(span: "Span") -> str:
    """Span -> W3C `traceparent` header value (ids zero-padded)."""
    return f"00-{span.trace_id:0>32}-{span.span_id:0>16}-01"


def format_traceparent(ctx: TraceContext) -> str:
    """TraceContext -> W3C `traceparent` value — the wire form carried
    inside busnet RPC envelopes and gossip payloads (runtime/busnet.py,
    parallel/cluster.py), symmetric with `extract_traceparent`."""
    return f"00-{ctx.trace_id:0>32}-{ctx.span_id:0>16}-01"


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    operation: str
    start_ms: float
    end_ms: Optional[float] = None
    tags: Dict[str, str] = field(default_factory=dict)
    logs: List[str] = field(default_factory=list)

    @property
    def duration_ms(self) -> float:
        # snapshot the end once: `end_ms or time.time()` re-read the
        # clock on every evaluation for unfinished spans, and the falsy
        # `or` treated end_ms == 0.0 as unfinished
        end = self.end_ms
        if end is None:
            end = time.time() * 1000
        return end - self.start_ms

    def context(self) -> TraceContext:
        return TraceContext(trace_id=self.trace_id, span_id=self.span_id)

    def to_dict(self) -> Dict:
        return {
            "traceId": self.trace_id, "spanId": self.span_id,
            "parentId": self.parent_id, "operation": self.operation,
            "startMs": self.start_ms, "durationMs": self.duration_ms,
            "tags": dict(self.tags), "logs": list(self.logs),
        }


class Tracer:
    """Per-thread active-span stacks + bounded finished-span buffer.

    The stacks are keyed by thread ident in a plain dict (not
    ``threading.local``): feeder/stager threads die on engine restart,
    and a thread-local would strand their entries invisibly — worse,
    idents recycle, so a reused ident could adopt a dead thread's stale
    parentage.  ``finished()``/``stats()`` sweep stacks whose thread no
    longer exists (thread hygiene; regression-tested)."""

    def __init__(self, capacity: int = 4096):
        self._finished: Deque[Span] = deque(maxlen=capacity)
        self._stacks: Dict[int, List[Span]] = {}
        self._lock = threading.Lock()
        self.error_count = 0
        self.finished_count = 0

    def _stack(self) -> List[Span]:
        ident = threading.get_ident()
        stack = self._stacks.get(ident)
        if stack is None:
            with self._lock:
                stack = self._stacks.setdefault(ident, [])
        return stack

    def _sweep_dead_threads(self) -> None:
        """Drop per-thread stacks whose thread is gone. Caller holds
        ``self._lock``."""
        if not self._stacks:
            return
        live = {t.ident for t in threading.enumerate()}
        for ident in [i for i in self._stacks if i not in live]:
            del self._stacks[ident]

    @contextlib.contextmanager
    def span(self, operation: str,
             parent: Optional[TraceContext] = None, **tags: str):
        stack = self._stack()
        if parent is None:
            active = stack[-1] if stack else None
            if active is not None:
                parent = active.context()
        span = Span(
            trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent else None,
            operation=operation,
            start_ms=time.time() * 1000,
            # defensive copy: tag values are stringified here so later
            # mutation of caller-held objects can't rewrite history
            tags={str(k): str(v) for k, v in tags.items()},
        )
        stack.append(span)
        errored = False
        try:
            yield span
        except BaseException as exc:
            errored = True
            span.tags["error"] = "true"
            span.logs.append(repr(exc))
            raise
        finally:
            span.end_ms = time.time() * 1000
            stack.pop()
            with self._lock:
                self._finished.append(span)
                self.finished_count += 1
                if errored or span.tags.get("error") == "true":
                    self.error_count += 1
                    errored = True
            if errored:
                # error spans surface in the metrics registry so the
                # scrape path sees them without dumping the span buffer
                from .metrics import GLOBAL_METRICS
                GLOBAL_METRICS.counter("tracing.span_errors").inc()

    def active(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def active_context(self) -> Optional[TraceContext]:
        span = self.active()
        return span.context() if span is not None else None

    def current_traceparent(self) -> Optional[str]:
        """W3C `traceparent` of this thread's active span (None when no
        span is open) — what busnet RPC envelopes stamp."""
        span = self.active()
        return inject_traceparent(span) if span is not None else None

    def finished(self, limit: int = 100) -> List[Dict]:
        with self._lock:
            self._sweep_dead_threads()
            spans = list(self._finished)[-limit:]
        return [s.to_dict() for s in spans]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            self._sweep_dead_threads()
            return {"finished": self.finished_count,
                    "errors": self.error_count,
                    "thread_stacks": len(self._stacks)}


GLOBAL_TRACER = Tracer()
