"""Lightweight distributed-tracing spans.

Reference: OpenTracing + Jaeger spans around lifecycle ops and gRPC calls
(sitewhere-grpc-model tracing/ServerTracingInterceptor.java,
TracerUtils.java:17-37). Here: in-proc span tree with a ring-buffer exporter
that the REST API can dump; `jax.profiler` traces cover the on-device side
(pipeline exposes start_device_trace/stop_device_trace).
"""

from __future__ import annotations

import contextlib
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    operation: str
    start_ms: float
    end_ms: Optional[float] = None
    tags: Dict[str, str] = field(default_factory=dict)
    logs: List[str] = field(default_factory=list)

    @property
    def duration_ms(self) -> float:
        return ((self.end_ms or time.time() * 1000) - self.start_ms)

    def to_dict(self) -> Dict:
        return {
            "traceId": self.trace_id, "spanId": self.span_id,
            "parentId": self.parent_id, "operation": self.operation,
            "startMs": self.start_ms, "durationMs": self.duration_ms,
            "tags": dict(self.tags), "logs": list(self.logs),
        }


class Tracer:
    """Thread-local active-span stack + bounded finished-span buffer."""

    def __init__(self, capacity: int = 4096):
        self._finished: Deque[Span] = deque(maxlen=capacity)
        self._local = threading.local()
        self._lock = threading.Lock()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, operation: str, **tags: str):
        stack = self._stack()
        parent = stack[-1] if stack else None
        span = Span(
            trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent else None,
            operation=operation,
            start_ms=time.time() * 1000,
            tags={k: str(v) for k, v in tags.items()},
        )
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.tags["error"] = "true"
            span.logs.append(repr(exc))
            raise
        finally:
            span.end_ms = time.time() * 1000
            stack.pop()
            with self._lock:
                self._finished.append(span)

    def active(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def finished(self, limit: int = 100) -> List[Dict]:
        with self._lock:
            spans = list(self._finished)[-limit:]
        return [s.to_dict() for s in spans]


GLOBAL_TRACER = Tracer()
