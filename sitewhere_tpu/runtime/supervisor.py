"""Gang-restart supervision for cluster serve processes.

The reference recovers a lost microservice process with zero operator
action: Kafka consumer-group rebalance hands its partitions to the
survivors (sitewhere-microservice kafka/MicroserviceKafkaConsumer.java:88)
and topology-reactive gRPC channels re-route
(sitewhere-grpc-client ApiDemux.java:183-227). An SPMD gang has no
partial-membership mode — the honest TPU answer is gang restart: a lost
peer turns into a deliberate, distinct exit on EVERY host (the peer
watchdog, parallel/cluster.py PeerWatchdog), and a per-host supervisor
restarts its serve child until the gang re-forms and recovers from
durable state (per-host shard checkpoint + committed-offset replay).

`python -m sitewhere_tpu serve --supervise ...` wraps the serve process
in this loop; run it on every cluster host and a hard-killed process
anywhere recovers the whole instance with no operator action
(tests/test_supervised_cluster.py drills kill-1-of-3).

Restart policy: restart on ANY abnormal exit (peer-loss code, crash,
signal); exit 0 is a graceful shutdown and ends supervision. A child
that keeps dying faster than `min_uptime_s` is broken (bad flags,
unbindable port), not failed — after `max_fast_fails` consecutive fast
deaths the supervisor gives up with the child's exit code.
"""

from __future__ import annotations

import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional

_PREFIX = "supervisor:"


class Supervisor:
    """Restart-on-abnormal-exit loop around one child command."""

    def __init__(self, child_argv: List[str], backoff_s: float = 1.0,
                 min_uptime_s: float = 5.0, max_fast_fails: int = 10):
        self.child_argv = list(child_argv)
        self.backoff_s = backoff_s
        self.min_uptime_s = min_uptime_s
        self.max_fast_fails = max_fast_fails
        self._stopping = threading.Event()
        self._stop_signum = signal.SIGTERM
        self._child: Optional[subprocess.Popen] = None

    def _log(self, msg: str) -> None:
        print(f"{_PREFIX} {msg}", flush=True)

    def _forward(self, signum, _frame) -> None:
        """First signal: graceful — forward to the child and stop
        supervising once it exits. Second signal: restore the default
        disposition so the operator can force-kill a hung shutdown
        (mirrors __main__._install_stop_handlers)."""
        self._stop_signum = signum
        self._stopping.set()
        signal.signal(signum, signal.SIG_DFL)
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
            except OSError:
                pass

    def run(self) -> int:
        signal.signal(signal.SIGTERM, self._forward)
        signal.signal(signal.SIGINT, self._forward)
        fast_fails = 0
        attempt = 0
        while True:
            # a stop signal that landed between children (child is None
            # or already reaped) must not spawn another one
            if self._stopping.is_set():
                return 0
            attempt += 1
            started = time.monotonic()
            # child inherits stdout/stderr: the serve banner (REST/bus
            # ports) stays visible to operators and drill tests
            self._child = subprocess.Popen(self.child_argv)
            self._log(f"child pid={self._child.pid} started "
                      f"(attempt {attempt})")
            if self._stopping.is_set():
                # stop signal raced the spawn: the handler saw the old
                # child (or None) — forward to the fresh one ourselves
                try:
                    self._child.send_signal(self._stop_signum)
                except OSError:
                    pass
            rc = self._child.wait()
            uptime = time.monotonic() - started
            if self._stopping.is_set():
                self._log(f"child exited rc={rc} during shutdown")
                return rc if rc is not None else 0
            if rc == 0:
                self._log("child exited cleanly; supervision complete")
                return 0
            if uptime < self.min_uptime_s:
                fast_fails += 1
                if fast_fails >= self.max_fast_fails:
                    self._log(
                        f"child died {fast_fails}x within "
                        f"{self.min_uptime_s:.0f}s (last rc={rc}); "
                        f"giving up")
                    return rc
            else:
                fast_fails = 0
            self._log(f"child exited rc={rc} after {uptime:.1f}s; "
                      f"restarting in {self.backoff_s:.1f}s")
            # interruptible backoff: a SIGTERM during the wait must not
            # spawn another child
            if self._stopping.wait(self.backoff_s):
                return 0


def supervise_serve(argv: List[str], backoff_s: float = 1.0,
                    min_uptime_s: float = 5.0,
                    max_fast_fails: int = 10) -> int:
    """Re-exec this interpreter's serve command (argv WITHOUT
    --supervise) under a Supervisor."""
    child_argv = [sys.executable, "-m", "sitewhere_tpu"] + list(argv)
    return Supervisor(child_argv, backoff_s=backoff_s,
                      min_uptime_s=min_uptime_s,
                      max_fast_fails=max_fast_fails).run()
