"""Centralized instance logging over the event bus.

Reference: MicroserviceLogProducer.java:33-47 — every microservice pushes
structured log records onto the `instance-logging` Kafka topic through a
bounded queue + background thread, and the admin surface reads the merged
stream. Here `BusLogHandler` is a stdlib logging.Handler doing the same onto
the in-proc bus topic (runtime/bus.py TopicNaming.instance_logging), and
`LogAggregator` tails the topic into a ring buffer the REST API serves
(GET /api/instance/logs).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from sitewhere_tpu.runtime.bus import EventBus, TopicNaming


class BusLogHandler(logging.Handler):
    """Publish log records to the instance-logging topic.

    Non-blocking like the reference's queue+thread: records append to a
    bounded deque drained by a daemon thread, so logging in the hot path
    never waits on the bus (overflow drops oldest, counted)."""

    def __init__(self, bus: EventBus, naming: Optional[TopicNaming] = None,
                 source: str = "instance", max_queue: int = 10_000,
                 level: int = logging.INFO):
        super().__init__(level=level)
        self.bus = bus
        self.naming = naming or TopicNaming()
        self.source = source
        self.dropped = 0
        self._queue: Deque[bytes] = deque(maxlen=max_queue)
        self._event = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="bus-log-producer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def emit(self, record: logging.LogRecord) -> None:
        try:
            payload = json.dumps({
                "ts_ms": int(record.created * 1000),
                "level": record.levelname,
                "logger": record.name,
                "source": self.source,
                "message": record.getMessage(),
                "thread": record.threadName,
            }).encode()
        except Exception:  # formatting must never raise into callers
            self.handleError(record)
            return
        if len(self._queue) == self._queue.maxlen:
            self.dropped += 1
        self._queue.append(payload)
        self._event.set()

    def _drain(self) -> None:
        topic = self.naming.instance_logging()
        while not self._stop.is_set():
            self._event.wait(timeout=0.5)
            self._event.clear()
            while self._queue:
                payload = self._queue.popleft()
                try:
                    self.bus.publish(topic, self.source.encode(), payload)
                except Exception:
                    self.dropped += 1


class LogAggregator:
    """Tail the instance-logging topic into a queryable ring buffer — the
    admin-facing merged log view (the reference aggregates the Kafka topic
    the same way). Built on the shared ConsumerHost poll loop
    (runtime/bus.py) so offset tracking and restart semantics are the same
    as every other consumer."""

    def __init__(self, bus: EventBus, naming: Optional[TopicNaming] = None,
                 capacity: int = 5000):
        from sitewhere_tpu.runtime.bus import ConsumerHost
        self.bus = bus
        self.naming = naming or TopicNaming()
        self._records: Deque[Dict[str, Any]] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._host = ConsumerHost(bus, self.naming.instance_logging(),
                                  group_id="log-aggregator",
                                  handler=self._consume)

    def start(self) -> None:
        self._host.start()

    def stop(self) -> None:
        self._host.stop()

    def _consume(self, records) -> None:
        for record in records:
            try:
                entry = json.loads(record.value)
            except ValueError:
                entry = {"message": record.value.decode("utf-8", "replace")}
            with self._lock:
                self._records.append(entry)

    def recent(self, limit: int = 200, level: Optional[str] = None,
               source: Optional[str] = None) -> List[Dict[str, Any]]:
        if limit <= 0:
            return []
        with self._lock:
            records = list(self._records)
        if level:
            records = [r for r in records if r.get("level") == level]
        if source:
            records = [r for r in records if r.get("source") == source]
        return records[-limit:]
