"""Core runtime: lifecycle tree, config, metrics/tracing, event bus, engines.

Reference layer L1 (sitewhere-core-lifecycle, sitewhere-microservice,
sitewhere-configuration) rebuilt for an in-process, TPU-hosted deployment:
services are lifecycle components inside one process per host, the event data
plane is an in-proc/file-backed partitioned log instead of Kafka brokers, and
configuration is layered files/dicts with live-reload instead of ZooKeeper XML.
"""

from sitewhere_tpu.runtime.lifecycle import (
    LifecycleComponent,
    LifecycleStatus,
    CompositeLifecycleStep,
    LifecycleProgressMonitor,
)
from sitewhere_tpu.runtime.bus import EventBus, Topic, TopicNaming, ConsumerGroup
from sitewhere_tpu.runtime.config import Configuration
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.tracing import Tracer, Span

__all__ = [name for name in dir() if not name.startswith("_")]
