"""Self-describing configuration metamodel.

The reference ships a machine-readable model of every microservice's
configuration surface — element roles, attributes, types, defaults — that the
admin UI renders into config editors and the server validates uploads
against (sitewhere-configuration: model/ConfigurationModelProvider.java,
per-service *ModelProvider + *Roles classes, 22 XSD namespaces).

This module is the TPU rebuild's equivalent over the layered JSON config
(runtime/config.py): each component contributes an `ElementModel` tree under
a role, the instance aggregates them into one JSON-able model, and
`validate_config` checks a configuration dict against it (types, required
attributes, unknown keys, choice constraints).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class AttributeType(str, enum.Enum):
    """Attribute datatypes (reference: configuration model AttributeType)."""

    STRING = "string"
    INTEGER = "integer"
    DECIMAL = "decimal"
    BOOLEAN = "boolean"
    SCRIPT = "script"          # name of a registered script
    DEVICE_TYPE_REF = "deviceTypeRef"
    ZONE_REF = "zoneRef"
    MEASUREMENT_REF = "measurementRef"


_PY_TYPES = {
    AttributeType.STRING: (str,),
    AttributeType.INTEGER: (int,),
    AttributeType.DECIMAL: (int, float),
    AttributeType.BOOLEAN: (bool,),
    AttributeType.SCRIPT: (str,),
    AttributeType.DEVICE_TYPE_REF: (str,),
    AttributeType.ZONE_REF: (str,),
    AttributeType.MEASUREMENT_REF: (str,),
}


@dataclass
class AttributeModel:
    """One configurable attribute (reference: AttributeNode)."""

    name: str
    type: AttributeType = AttributeType.STRING
    description: str = ""
    required: bool = False
    default: Any = None
    choices: Optional[List[Any]] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name, "type": self.type.value,
                               "required": self.required}
        if self.description:
            out["description"] = self.description
        if self.default is not None:
            out["default"] = self.default
        if self.choices:
            out["choices"] = list(self.choices)
        return out


@dataclass
class ElementModel:
    """One configurable element (reference: ElementNode): a named section of
    the config dict, with attributes and child elements."""

    name: str
    role: str
    description: str = ""
    attributes: List[AttributeModel] = field(default_factory=list)
    children: List["ElementModel"] = field(default_factory=list)
    multiple: bool = False      # element is a list of instances
    optional: bool = True

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "role": self.role,
            "description": self.description,
            "multiple": self.multiple,
            "optional": self.optional,
            "attributes": [a.to_json() for a in self.attributes],
            "children": [c.to_json() for c in self.children],
        }


def _attr(name, type=AttributeType.STRING, required=False, default=None,
          choices=None, description=""):
    return AttributeModel(name=name, type=type, required=required,
                          default=default, choices=choices,
                          description=description)


_I, _D, _B = AttributeType.INTEGER, AttributeType.DECIMAL, AttributeType.BOOLEAN


def pipeline_model() -> ElementModel:
    """Fused TPU pipeline engine (pipeline/engine.py ctor surface)."""
    return ElementModel(
        name="pipeline", role="pipeline",
        description="Fused TPU hot-path engine",
        attributes=[
            _attr("batch_size", _I, default=8192),
            _attr("mode", choices=["throughput", "latency"],
                  default="throughput",
                  description="throughput: full batches via the pipelined "
                              "feeder; latency: the engine boots at "
                              "latency_batch_size and ingest flushes "
                              "adaptively (fill or linger_ms) for a p99 "
                              "ingest->alert budget"),
            _attr("latency_batch_size", _I, default=4096),
            _attr("linger_ms", _D, default=2.0,
                  description="latency mode: max ms an offered event "
                              "waits before a partial batch flushes"),
            _attr("measurement_slots", _I, default=32),
            _attr("max_tenants", _I, default=16),
            _attr("max_threshold_rules", _I, default=256),
            _attr("max_geofence_rules", _I, default=256),
            _attr("presence_missing_interval_ms", _I,
                  default=8 * 60 * 60 * 1000,
                  description="DevicePresenceManager missing interval"),
            _attr("geofence_impl", choices=["auto", "xla", "pallas",
                                            "pallas_interpret"],
                  default="auto"),
            _attr("shards", _I, default=1,
                  description="mesh size for ShardedPipelineEngine"),
            _attr("device_routing", choices=["auto", "on", "off"],
                  default="auto",
                  description="on-device shard routing (radix bucket + "
                              "ICI all_to_all in the fused step) instead "
                              "of the host arena router; auto = on for "
                              "multi-shard single-controller meshes"),
            _attr("h2d_buffer_depth", _I, default=3,
                  description="on-device H2D staging-ring depth "
                              "(pipeline/staging.py): how many host->"
                              "device transfers may be in flight so "
                              "batch N+1's transfer overlaps batch N's "
                              "compute; 1 = serial transfers (the "
                              "differential baseline), 2-3 typical — "
                              "see docs/PERF.md"),
        ])


def event_sources_model() -> ElementModel:
    receiver_children = [
        ElementModel(
            name="mqtt", role="event-source-receiver", multiple=True,
            description="In-proc MQTT subscription receiver",
            attributes=[_attr("topic", required=True),
                        _attr("qos", _I, default=0)]),
        ElementModel(
            name="socket", role="event-source-receiver", multiple=True,
            attributes=[_attr("port", _I, required=True),
                        _attr("host", default="0.0.0.0")]),
        ElementModel(
            name="http", role="event-source-receiver", multiple=True,
            attributes=[_attr("port", _I, required=True),
                        _attr("path", default="/events")]),
        ElementModel(
            name="coap", role="event-source-receiver", multiple=True,
            attributes=[_attr("port", _I, required=True)]),
        ElementModel(
            name="websocket", role="event-source-receiver", multiple=True,
            attributes=[_attr("url", required=True)]),
        ElementModel(
            name="stomp_broker", role="event-source-receiver",
            multiple=True,
            description="EMBEDDED STOMP broker (the "
                        "ActiveMQBrokerEventReceiver slot): hosts the "
                        "broker in-process and consumes a destination",
            attributes=[_attr("port", _I, default=0),
                        _attr("host", default="127.0.0.1"),
                        _attr("destination",
                              default="/queue/sitewhere")]),
    ]
    decoder = ElementModel(
        name="decoder", role="event-source-decoder", optional=False,
        attributes=[
            _attr("type", required=True,
                  choices=["wire", "protobuf", "json-batch", "json-request",
                           "scripted", "composite"]),
            _attr("script", AttributeType.SCRIPT,
                  description="for type=scripted"),
        ])
    dedup = ElementModel(
        name="deduplicator", role="event-source-deduplicator",
        attributes=[_attr("type", choices=["alternate-id", "scripted"]),
                    _attr("script", AttributeType.SCRIPT)])
    return ElementModel(
        name="event_sources", role="event-sources", multiple=True,
        description="Inbound event sources (receivers + decoder + dedup)",
        attributes=[_attr("source_id", required=True),
                    _attr("bulk", _B, default=False,
                          description="use the bulk wire-ingest lane")],
        children=receiver_children + [decoder, dedup])


def event_management_model() -> ElementModel:
    # per-tenant store choice — the reference's DatastoreConfigurationParser
    # role (persist/datastore.py): a tenant either shares the instance log
    # or gets a dedicated columnar/memory store
    tenant_datastore = ElementModel(
        name="tenant_datastore", role="tenant-datastore", multiple=True,
        description="Dedicated event store for one tenant",
        attributes=[
            _attr("tenant", required=True),
            _attr("kind", choices=["columnar", "memory", "widerow"],
                  default="columnar",
                  description="columnar scan log, in-memory log, or the "
                              "wide-row ACID store (the HBase/Cassandra "
                              "historical-store role)"),
            _attr("data_dir",
                  description="spill dir / db path (relative = under "
                              "instance dir)"),
            _attr("segment_rows", _I, default=65536),
            _attr("linger_ms", _I, default=250),
            _attr("spill", _B, default=True),
            _attr("bucket_ms", _I, default=3_600_000,
                  description="widerow time-bucket width (retention "
                              "prunes whole buckets)"),
        ])
    return ElementModel(
        name="event_management", role="event-management",
        description="Columnar event log + indices",
        attributes=[
            _attr("data_dir", description="parquet spill directory"),
            _attr("segment_rows", _I, default=65536),
            _attr("spill", _B, default=True),
        ],
        children=[tenant_datastore])


def device_state_model() -> ElementModel:
    return ElementModel(
        name="device_state", role="device-state",
        attributes=[
            _attr("presence_missing_interval_ms", _I,
                  default=8 * 60 * 60 * 1000),
            _attr("presence_check_interval_ms", _I, default=10 * 60 * 1000),
        ])


def rule_processing_model() -> ElementModel:
    return ElementModel(
        name="rules", role="rule-processing", multiple=True,
        description="Threshold + geofence rule definitions",
        attributes=[_attr("token", required=True),
                    _attr("type", required=True,
                          choices=["threshold", "geofence", "scripted"]),
                    _attr("measurement_name", AttributeType.MEASUREMENT_REF),
                    _attr("operator",
                          choices=[">", ">=", "<", "<=", "==", "!="]),
                    _attr("threshold", _D),
                    _attr("zone_token", AttributeType.ZONE_REF),
                    _attr("condition", choices=["inside", "outside"]),
                    _attr("alert_level", _I),
                    _attr("alert_type"),
                    _attr("script", AttributeType.SCRIPT)])


def outbound_connectors_model() -> ElementModel:
    return ElementModel(
        name="outbound_connectors", role="outbound-connectors", multiple=True,
        attributes=[_attr("connector_id", required=True),
                    _attr("type", required=True,
                          choices=["mqtt", "http-post", "event-index",
                                   "scripted", "collecting"]),
                    _attr("topic"), _attr("url"),
                    _attr("num_threads", _I, default=1)],
        children=[ElementModel(
            name="filters", role="outbound-connector-filter", multiple=True,
            attributes=[_attr("type", required=True,
                              choices=["device-type", "area", "scripted"]),
                        _attr("token"), _attr("operation",
                                              choices=["include", "exclude"]),
                        _attr("script", AttributeType.SCRIPT)])])


def command_delivery_model() -> ElementModel:
    return ElementModel(
        name="command_delivery", role="command-delivery",
        children=[
            ElementModel(
                name="router", role="command-router",
                attributes=[_attr("type", default="device-type-mapping",
                                  choices=["device-type-mapping",
                                           "single-destination"])]),
            ElementModel(
                name="destinations", role="command-destination",
                multiple=True,
                attributes=[_attr("destination_id", required=True),
                            _attr("type", required=True,
                                  choices=["mqtt", "coap", "sms", "inproc"]),
                            _attr("topic_prefix"),
                            _attr("sms_from_number",
                                  description="for type=sms"),
                            _attr("device_type",
                                  AttributeType.DEVICE_TYPE_REF)]),
        ])


def registration_model() -> ElementModel:
    return ElementModel(
        name="registration", role="device-registration",
        attributes=[
            _attr("allow_new_devices", _B, default=True),
            _attr("auto_assign", _B, default=True),
            _attr("default_device_type", AttributeType.DEVICE_TYPE_REF),
        ])


def batch_operations_model() -> ElementModel:
    return ElementModel(
        name="batch_operations", role="batch-operations",
        attributes=[_attr("throttle_delay_ms", _I, default=0),
                    _attr("num_threads", _I, default=2)])


def schedule_model() -> ElementModel:
    return ElementModel(
        name="schedules", role="schedule-management",
        attributes=[_attr("tick_interval_s", _D, default=1.0)])


def label_generation_model() -> ElementModel:
    return ElementModel(
        name="labels", role="label-generation", multiple=True,
        attributes=[_attr("generator_id", default="qrcode"),
                    _attr("scale", _I, default=8),
                    _attr("border", _I, default=4),
                    _attr("ec_level", choices=["L", "M", "Q", "H"],
                          default="M")])


def web_rest_model() -> ElementModel:
    return ElementModel(
        name="web", role="web-rest",
        attributes=[_attr("port", _I, default=8080),
                    _attr("jwt_expiration_s", _I, default=3600)])


def analytics_model() -> ElementModel:
    return ElementModel(
        name="analytics", role="analytics",
        attributes=[_attr("window_ms", _I, default=60_000),
                    _attr("slide_ms", _I, default=10_000)])


def event_search_model() -> ElementModel:
    return ElementModel(
        name="search_providers", role="event-search", multiple=True,
        description="Federated event-search providers (the in-process "
                    "columnar provider is always registered; type=http "
                    "adds an external engine, the SolrSearchProvider "
                    "role)",
        attributes=[_attr("provider_id", required=True),
                    _attr("type", required=True,
                          choices=["http"]),
                    _attr("base_url", required=True),
                    _attr("name"),
                    _attr("timeout_s", _D, default=10.0),
                    _attr("tenant")])


def telemetry_model() -> ElementModel:
    return ElementModel(
        name="telemetry", role="instance-telemetry",
        description="Opt-in usage telemetry (the MicroserviceAnalytics "
                    "role): lifecycle Started/Uptime/Stopped events "
                    "POSTed to the OPERATOR'S endpoint; off by default, "
                    "no third-party service, lifecycle metadata only",
        attributes=[_attr("enabled", _B, default=False),
                    _attr("endpoint",
                          description="HTTP(S) URL receiving the JSON "
                                      "events (required when enabled)"),
                    _attr("interval_s", _D, default=3600.0)])


def observability_model() -> ElementModel:
    return ElementModel(
        name="observability", role="instance-observability",
        description="Tracing + event-age telemetry knobs (the flight "
                    "recorder and metrics registry are always on; this "
                    "controls the optional extras)",
        attributes=[
            _attr("trace_sample_n", _I, default=0,
                  description="sample 1-in-N ingest deliveries with a "
                              "journey span that propagates over busnet "
                              "(W3C traceparent); 0 disables sampling"),
        ])


def faults_model() -> ElementModel:
    """Deterministic fault injection + ingest admission (runtime/faults.py,
    sources/manager.py AdmissionController; docs/OPERATIONS.md
    "Fault drills")."""
    rule = ElementModel(
        name="rules", role="fault-rule", multiple=True,
        description="One fault-point schedule entry",
        attributes=[
            _attr("point", required=True,
                  description="fault point name (runtime/faults.py "
                              "FAULT_POINTS)"),
            _attr("p", _D, default=1.0,
                  description="per-hit firing probability (seeded RNG)"),
            _attr("times", _I,
                  description="stop after this many firings"),
            _attr("after", _I, default=0,
                  description="skip the first N hits"),
            _attr("delay_s", _D, default=0.0,
                  description="stall instead of raising (delay points)"),
            _attr("duration_s", _D, default=0.0,
                  description="window mode: keep firing for this long "
                              "after the first firing"),
        ])
    return ElementModel(
        name="faults", role="fault-injection",
        description="Seeded fault drills + overload admission control",
        attributes=[
            _attr("allow_drills", _B, default=False,
                  description="enable POST /api/instance/faults (403 "
                              "otherwise)"),
            _attr("seed", _I, default=0,
                  description="seed for the boot-armed fault plan"),
            _attr("admission_step_budget_ms", _D,
                  description="shed ingest when mean step sync cost "
                              "exceeds this (flight rollups)"),
            _attr("admission_queue_depth_budget", _I,
                  description="shed ingest when decoded-events backlog "
                              "exceeds this"),
        ],
        children=[rule])


def feeders_model() -> ElementModel:
    """Disaggregated feeder fleet (feeders/; docs/FEEDERS.md): remote
    workers own TTL-leased source partitions, decode+intern+pack locally,
    and ship ready-to-stage wire blobs to the mesh host's bus edge."""
    return ElementModel(
        name="feeders", role="feeder-fleet",
        description="Disaggregated input feeders: lease-owned partition "
                    "decode/pack off the mesh host, blob handoff over "
                    "busnet with exactly-once takeover replay",
        attributes=[
            _attr("enabled", _B, default=False,
                  description="mount the feeder_* ops on the bus edge "
                              "(requires bus.edge_port)"),
            _attr("frames_topic",
                  description="raw wire-frame topic feeders consume "
                              "(default: the instance feeder-frames "
                              "topic)"),
            _attr("lease_ttl_s", _D, default=5.0,
                  description="partition lease TTL; a worker renews at "
                              "TTL/3 and a lapsed lease is stealable at "
                              "a higher epoch"),
            _attr("connect",
                  description="worker mode: mesh host bus edge "
                              "host:port (serve --feeder)"),
            _attr("name",
                  description="worker identity for leases (default "
                              "host:pid)"),
            _attr("partitions",
                  description="worker mode: csv partition pin, e.g. "
                              "'0,1'; unset leases every partition"),
            _attr("poll_max_records", _I, default=4096),
            _attr("shed_backoff_s", _D, default=0.25,
                  description="worker backoff after a propagated "
                              "admission shed (structured 429)"),
        ])


def _all_elements() -> List[ElementModel]:
    """Every subsystem's element model — the single source both the UI model
    and the validator consume."""
    return [
        pipeline_model(), event_sources_model(), event_management_model(),
        device_state_model(), rule_processing_model(),
        outbound_connectors_model(), command_delivery_model(),
        registration_model(), batch_operations_model(), schedule_model(),
        label_generation_model(), web_rest_model(), analytics_model(),
        event_search_model(), telemetry_model(), observability_model(),
        faults_model(), feeders_model(),
    ]


def instance_configuration_model() -> Dict[str, Any]:
    """The aggregated, JSON-able model for the whole instance — what the
    admin UI fetches (reference: instance-wide configuration model
    aggregation of every microservice's *ModelProvider)."""
    elements = _all_elements()
    return {
        "modelVersion": 1,
        "elements": [e.to_json() for e in elements],
        "roles": sorted({r for e in elements for r in _roles_of(e)}),
    }


def _roles_of(element: ElementModel) -> List[str]:
    out = [element.role]
    for child in element.children:
        out.extend(_roles_of(child))
    return out


# -- validation ---------------------------------------------------------------

@dataclass
class ValidationIssue:
    path: str
    message: str

    def to_json(self) -> Dict[str, str]:
        return {"path": self.path, "message": self.message}


def _validate_element(cfg: Any, model: ElementModel, path: str,
                      issues: List[ValidationIssue]) -> None:
    if model.multiple:
        if not isinstance(cfg, list):
            issues.append(ValidationIssue(path, "expected a list"))
            return
        for i, item in enumerate(cfg):
            _validate_single(item, model, f"{path}[{i}]", issues)
    else:
        _validate_single(cfg, model, path, issues)


def _validate_single(cfg: Any, model: ElementModel, path: str,
                     issues: List[ValidationIssue]) -> None:
    if not isinstance(cfg, dict):
        issues.append(ValidationIssue(path, "expected an object"))
        return
    attrs = {a.name: a for a in model.attributes}
    children = {c.name: c for c in model.children}
    for key, value in cfg.items():
        if key in attrs:
            a = attrs[key]
            ok_types = _PY_TYPES[a.type]
            if a.type is not AttributeType.BOOLEAN \
                    and isinstance(value, bool):
                issues.append(ValidationIssue(
                    f"{path}.{key}", f"expected {a.type.value}, got boolean"))
            elif not isinstance(value, ok_types):
                issues.append(ValidationIssue(
                    f"{path}.{key}",
                    f"expected {a.type.value}, got {type(value).__name__}"))
            elif a.choices and value not in a.choices:
                issues.append(ValidationIssue(
                    f"{path}.{key}",
                    f"value {value!r} not one of {a.choices}"))
        elif key in children:
            _validate_element(value, children[key], f"{path}.{key}", issues)
        else:
            issues.append(ValidationIssue(f"{path}.{key}",
                                          "unknown configuration key"))
    for a in attrs.values():
        if a.required and a.name not in cfg:
            issues.append(ValidationIssue(
                f"{path}.{a.name}", "required attribute missing"))
    for c in children.values():
        if not c.optional and c.name not in cfg:
            issues.append(ValidationIssue(
                f"{path}.{c.name}", "required element missing"))


def validate_config(config: Dict[str, Any],
                    _allow_tenants: bool = True) -> List[ValidationIssue]:
    """Validate a configuration dict against the instance model. Top-level
    keys that no element claims are reported as unknown. A top-level
    `tenants.<id>` overlay revalidates recursively — but only one level
    deep, matching what runtime/config.py actually consumes (a nested
    tenants block inside an overlay is dead config and is flagged)."""
    elements = {e.name: e for e in _all_elements()}
    issues: List[ValidationIssue] = []
    for key, value in config.items():
        if key == "tenants" and _allow_tenants:
            if not isinstance(value, dict):
                issues.append(ValidationIssue("tenants", "expected an object"))
                continue
            for tenant, overlay in value.items():
                if isinstance(overlay, dict):
                    issues.extend(
                        _prefixed(validate_config(overlay,
                                                  _allow_tenants=False),
                                  f"tenants.{tenant}"))
                else:
                    issues.append(ValidationIssue(
                        f"tenants.{tenant}", "expected an object"))
        elif key in elements:
            _validate_element(value, elements[key], key, issues)
        else:
            issues.append(ValidationIssue(key, "unknown configuration key"))
    return issues


def _prefixed(issues: List[ValidationIssue],
              prefix: str) -> List[ValidationIssue]:
    return [ValidationIssue(f"{prefix}.{i.path}", i.message) for i in issues]
