"""Layered configuration system.

Replaces the reference's ZooKeeper-resident XML + Spring namespace parsers
(sitewhere-configuration ConfigurationContentParser.java, ConfigurationMonitor.java:37-90)
with layered JSON/dict sources: defaults <- instance file <- service section <-
tenant section <- environment variables, plus a watch thread that live-reloads
changed files and fires callbacks (the reference restarts components on ZK
TreeCache change events; here listeners decide what to restart).

Keys are dotted paths, e.g. ``pipeline.batch_size`` or
``tenants.<tenant>.rules.geofence.max_zones``.
"""

from __future__ import annotations

import copy
import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional


def _deep_merge(base: Dict, overlay: Dict) -> Dict:
    out = dict(base)
    for key, val in overlay.items():
        if isinstance(val, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], val)
        else:
            out[key] = val
    return out


class Configuration:
    """Layered dotted-key configuration with optional file watching."""

    ENV_PREFIX = "SWTPU_"  # SWTPU_PIPELINE__BATCH_SIZE=4096 -> pipeline.batch_size

    def __init__(self, defaults: Optional[Dict] = None,
                 config_path: Optional[str] = None,
                 use_env: bool = True):
        self._defaults = copy.deepcopy(defaults or {})
        self._config_path = config_path
        self._use_env = use_env
        self._overrides: Dict = {}
        self._listeners: List[Callable[["Configuration"], None]] = []
        self._lock = threading.RLock()
        self._watcher: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()
        self._file_mtime: Optional[float] = None
        self._merged: Dict = {}
        self._rebuild()

    # -- layering ------------------------------------------------------------

    def _load_file(self) -> Dict:
        if not self._config_path or not os.path.exists(self._config_path):
            return {}
        with open(self._config_path, "r", encoding="utf-8") as fh:
            return json.load(fh)

    def _load_env(self) -> Dict:
        out: Dict = {}
        if not self._use_env:
            return out
        for key, val in os.environ.items():
            if not key.startswith(self.ENV_PREFIX):
                continue
            path = key[len(self.ENV_PREFIX):].lower().split("__")
            node = out
            for part in path[:-1]:
                node = node.setdefault(part, {})
            try:
                node[path[-1]] = json.loads(val)
            except (ValueError, json.JSONDecodeError):
                node[path[-1]] = val
        return out

    def _rebuild(self) -> None:
        with self._lock:
            merged = self._defaults
            merged = _deep_merge(merged, self._load_file())
            merged = _deep_merge(merged, self._load_env())
            merged = _deep_merge(merged, self._overrides)
            self._merged = merged

    # -- access --------------------------------------------------------------

    def get(self, dotted_key: str, default: Any = None) -> Any:
        with self._lock:
            node: Any = self._merged
            for part in dotted_key.split("."):
                if not isinstance(node, dict) or part not in node:
                    return default
                node = node[part]
            return node

    def section(self, dotted_key: str) -> Dict:
        val = self.get(dotted_key, {})
        return copy.deepcopy(val) if isinstance(val, dict) else {}

    def tenant_section(self, tenant_token: str, dotted_key: str = "") -> Dict:
        """Per-tenant overlay (reference: per-tenant ZK config subtree)."""
        base = self.section(dotted_key) if dotted_key else {}
        suffix = f".{dotted_key}" if dotted_key else ""
        overlay = self.section(f"tenants.{tenant_token}{suffix}")
        return _deep_merge(base, overlay)

    def set(self, dotted_key: str, value: Any) -> None:
        """Programmatic override (highest-priority layer); fires listeners."""
        with self._lock:
            node = self._overrides
            parts = dotted_key.split(".")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = value
            self._rebuild()
        self._fire()

    def snapshot(self) -> Dict:
        with self._lock:
            return copy.deepcopy(self._merged)

    # -- change notification -------------------------------------------------

    def add_listener(self, callback: Callable[["Configuration"], None]) -> None:
        self._listeners.append(callback)

    def _fire(self) -> None:
        for callback in list(self._listeners):
            callback(self)

    def start_watching(self, interval_s: float = 2.0) -> None:
        """Poll the config file for mtime changes and live-reload (reference:
        ConfigurationMonitor TreeCache watch)."""
        if self._watcher or not self._config_path:
            return
        self._watch_stop.clear()

        def _watch() -> None:
            while not self._watch_stop.wait(interval_s):
                try:
                    mtime = os.path.getmtime(self._config_path)
                except OSError:
                    continue
                if self._file_mtime is None:
                    self._file_mtime = mtime
                    continue
                if mtime != self._file_mtime:
                    self._file_mtime = mtime
                    self._rebuild()
                    self._fire()

        if os.path.exists(self._config_path):
            self._file_mtime = os.path.getmtime(self._config_path)
        self._watcher = threading.Thread(target=_watch, name="config-watch", daemon=True)
        self._watcher.start()

    def stop_watching(self) -> None:
        self._watch_stop.set()
        if self._watcher:
            self._watcher.join(timeout=5)
            self._watcher = None


DEFAULTS: Dict = {
    "instance": {"id": "swtpu1", "product_id": "sitewhere-tpu",
                 "default_tenant": "default",
                 "admin_username": "admin", "admin_password": "password"},
    "pipeline": {
        "enabled": True,
        "batch_size": 8192,
        # "throughput" feeds full batches via the pipelined submitter;
        # "latency" boots the engine at latency_batch_size and ingest
        # flushes adaptively (fill or linger_ms) so one event's
        # ingest->rules->alert wall time meets a p99 budget
        # (pipeline/feed.py AdaptiveBatcher)
        "mode": "throughput",
        "latency_batch_size": 4096,
        "linger_ms": 2.0,
        # adaptive linger (pipeline/feed.py AdaptiveBatcher): dispatch a
        # complete offered burst immediately; linger_ms only bounds
        # coalescing behind an in-flight flush. False = classic fixed
        # linger (maximize coalescing for bursty multi-producer ingest)
        "adaptive_linger": True,
        # on-device shard routing (ops/route.py): "auto" turns it on for
        # real multi-shard single-controller meshes (single-chip and
        # multi-host keep the host arena route); "on"/"off" force it
        "device_routing": "auto",
        # H2D staging-ring depth (pipeline/staging.py): in-flight
        # host->device transfers; 1 = serial staging, 2-3 overlap the
        # transfer of batch N+1 with the compute of batch N (PERF.md)
        "h2d_buffer_depth": 3,
        "max_devices": 131072,
        "max_zones": 256,
        "max_zone_vertices": 32,
        "max_threshold_rules": 256,
        "max_measurement_names": 1024,
        "max_tenants": 16,
        "measurement_slots": 8,
        "presence_missing_interval_ms": 8 * 60 * 60 * 1000,  # reference default 8h
    },
    "bus": {"partitions": 8, "retention_chunks": 64, "chunk_events": 65536,
            "edge_port": None},  # set to expose the bus on TCP (busnet)
    # disaggregated feeder fleet (feeders/): remote workers own TTL-leased
    # source partitions and ship ready-to-stage wire blobs; the mesh host
    # does only H2D + step. `enabled` mounts the feeder_* busnet ops on
    # the bus edge (requires bus.edge_port). Worker-side keys (`connect`,
    # `name`, `partitions`) configure `serve --feeder` processes.
    "feeders": {
        "enabled": False,
        "frames_topic": None,      # default: TopicNaming.feeder_frames()
        "lease_ttl_s": 5.0,
        "connect": None,           # mesh host bus edge "host:port"
        "name": None,              # worker identity (default: host:pid)
        "partitions": None,        # csv pin, e.g. "0,1"; None = all
        "poll_max_records": 4096,
        "shed_backoff_s": 0.25,
    },
    # fused pipeline rules applied at boot (list of dicts matching the
    # `rules` config-model element — runtime/config_model.py
    # rule_processing_model; same shape as POST /api/rules bodies)
    "rules": [],
    # federated external search providers (runtime/config_model.py
    # event_search_model; search/external.py HttpSearchProvider)
    "search_providers": [],
    # opt-in usage telemetry (runtime/telemetry.py — the
    # MicroserviceAnalytics role, inverted to off-by-default and
    # operator-owned endpoint)
    "telemetry": {"enabled": False, "endpoint": None, "interval_s": 3600},
    # in-process observability (runtime/config_model.py
    # observability_model): sample 1 in N ingest deliveries into a
    # journey span stitched across busnet hops (runtime/tracing.py
    # traceparent propagation). 0 disables sampling entirely — the
    # disarmed path is one modulo per delivery.
    "observability": {"trace_sample_n": 0},
    # concurrent query serving tier (serving/, docs/SERVING.md): bounded
    # analytics readers behind per-tenant admission + the incremental
    # window-grid cache. latency_budget_ms 0 disables the p99 shed gate;
    # mesh_row_threshold None keeps the planner's measured default.
    "serving": {
        "workers": 4,
        "queue_depth_budget": 64,
        "latency_budget_ms": 0,
        "cache_mb": 64,
        "mesh_row_threshold": None,
    },
    # unattended drift-refit sweeps (actuation/refit.py
    # DriftRefitJobExecutor): interval in seconds between sweeps over the
    # installed anomaly models. OFF by default (None) — an autonomous
    # refit rewrites live model constants, so it is operator opt-in.
    "actuation": {"refit_interval_s": None},
    # deterministic fault injection + ingest admission (runtime/faults.py,
    # sources/manager.py AdmissionController; config_model faults_model;
    # docs/OPERATIONS.md "Fault drills"). Everything off by default:
    # fault_point() is a module-global load + identity test when disarmed
    # and admit() is two attribute loads when no budget is set.
    "faults": {"allow_drills": False, "seed": 0, "rules": [],
               "admission_step_budget_ms": None,
               "admission_queue_depth_budget": None},
    "persist": {"data_dir": "./swtpu-data",
                # seconds between automatic device-state checkpoints
                # (None = manual/REST-triggered only)
                "checkpoint_interval_s": 300},
    "api": {"host": "127.0.0.1", "port": 8080, "jwt_secret": "change-me",
            "jwt_expiration_min": 600},
    "mesh": {"shards": 1},
    # multi-host deployment (parallel/cluster.py): N OS processes form one
    # jax.distributed mesh; `coordinator` ("host:port") turns it on.
    # `peers` maps process id -> that host's bus-edge address
    # ("0=hostA:9092,1=hostB:9092").
    "cluster": {
        "coordinator": None,
        "num_processes": 1,
        "process_id": 0,
        "peers": None,
        "heartbeat_s": 1.0,
        "stale_after_s": 5.0,
        "fail_after_s": 15.0,
        "presence_every_ticks": 0,
        # a stale peer (or step-loop fatal) exits the process for the
        # supervisor to restart the gang — the TPU pod failure model
        "exit_on_peer_loss": True,
        "peer_loss_exit_code": 13,
        # leaderless cross-host registry replication (parallel/cluster.py
        # RegistryGossip): creates + assignment lifecycle broadcast to
        # peers and apply idempotently
        "registry_gossip": True,
    },
}
