"""Dead-letter operability: list / inspect / replay parked records.

Poison records park on `<topic>.dead-letter` after a consumer's retry
budget is exhausted (runtime/bus.py ConsumerHost, busnet
RemoteConsumerHost) and on `<topic>.misrouted` when cluster hosts disagree
on ownership (parallel/cluster.py). The reference makes reprocessing a
first-class pipeline input — `inbound-reprocess-events` is one of the
per-tenant topics (KafkaTopicNaming.java:48-69) that inbound processing
consumes alongside decoded events. This module is the operator surface
over that loop:

  list   -> every parked topic with its backlog (records past the replay
            cursor)
  read   -> inspect records (decoded preview when the value is the
            standard msgpack decoded-request envelope)
  replay -> republish parked records to their reprocess destination and
            advance the replay cursor (a committed consumer group on the
            dead-letter topic, so repeated replays take only NEW records)

The default replay destination: a parked `<decoded-events>.dead-letter`
record goes to the tenant's `inbound-reprocess-events` (consumed by
InboundProcessingService); anything else replays onto its base topic.
"""

from __future__ import annotations

import base64
from typing import Dict, List, Optional

import msgpack

from sitewhere_tpu.runtime.bus import EventBus, TopicNaming

REPLAY_GROUP = "dead-letter-replay"
_PARKED_SUFFIXES = (".dead-letter", ".misrouted")


def _replay_backlog(bus: EventBus, topic_name: str) -> int:
    """Records past the replay cursor (committed REPLAY_GROUP offsets)."""
    consumer = bus.consumer(topic_name, REPLAY_GROUP)
    end = bus.topic(topic_name).end_offsets()
    return sum(max(0, int(e) - int(c))
               for e, c in zip(end, consumer.committed))


def list_parked_topics(bus: EventBus,
                       naming: TopicNaming) -> List[Dict]:
    """Every dead-letter / misrouted topic with totals + replay backlog.

    Unions in-memory topics with on-disk ones: after a restart, parked
    records sit in durable logs no live component has touched yet — the
    post-crash triage this tool exists for."""
    names = set(bus.topics()) | set(bus.persisted_topics())
    out = []
    for name in sorted(names):
        if not name.endswith(_PARKED_SUFFIXES):
            continue
        topic = bus.topic(name)
        total = sum(int(e) for e in topic.end_offsets())
        if total == 0:
            continue
        out.append({
            "topic": name,
            "records": total,
            "replayBacklog": _replay_backlog(bus, name),
            "replayTarget": default_replay_target(name, naming),
        })
    return out


def _tenant_of(topic_name: str, naming: TopicNaming) -> Optional[str]:
    """Tenant token of a per-tenant topic name, None for global topics.
    Layout (bus.py TopicNaming): `<product>.<instance>.tenant.<t>.<suffix>`."""
    prefix = naming._tenant("", "")  # "<product>.<instance>.tenant.."
    prefix = prefix[:-1]             # trailing "." of empty suffix
    if not topic_name.startswith(prefix):
        return None
    rest = topic_name[len(prefix):]
    tenant, _, _suffix = rest.partition(".")
    return tenant or None


def default_replay_target(parked_topic: str, naming: TopicNaming) -> str:
    """Where a parked record should re-enter the pipeline."""
    base = parked_topic
    for suffix in _PARKED_SUFFIXES:
        if base.endswith(suffix):
            base = base[:-len(suffix)]
            break
    tenant = _tenant_of(base, naming)
    if tenant is not None and base == naming.event_source_decoded_events(
            tenant):
        # the reference's reprocess loop: decoded-event poison re-enters
        # through the dedicated reprocess topic, not the live ingest topic
        return naming.inbound_reprocess_events(tenant)
    return base


def _preview(value: bytes) -> Dict:
    """Best-effort decode for inspection: the standard decoded-request
    envelope renders as JSON-ish; anything else as base64."""
    try:
        data = msgpack.unpackb(value, raw=False)
        if isinstance(data, dict):
            return {"kind": "decoded-request",
                    "deviceToken": data.get("deviceToken"),
                    "requestKind": data.get("kind"),
                    "sourceId": data.get("sourceId"),
                    "fwdFrom": data.get("fwdFrom")}
    except Exception:
        pass
    return {"kind": "opaque",
            "base64": base64.b64encode(value[:512]).decode()}


def read_parked_records(bus: EventBus, topic_name: str,
                        limit: int = 100) -> List[Dict]:
    """Inspect (without consuming) the oldest parked records still behind
    the replay cursor."""
    topic = bus.topic(topic_name)
    consumer = bus.consumer(topic_name, REPLAY_GROUP)
    out: List[Dict] = []
    for p, partition in enumerate(topic.partitions):
        start = max(int(consumer.committed[p]), partition.start_offset())
        for offset, key, value, ts in partition.read(
                start, max(0, limit - len(out))):
            out.append({
                "partition": p, "offset": int(offset),
                "key": key.decode(errors="replace"),
                "timestamp_ms": int(ts),
                "size": len(value),
                "preview": _preview(value),
            })
            if len(out) >= limit:
                return out
    return out


def replay_parked_records(bus: EventBus, naming: TopicNaming,
                          topic_name: str,
                          target: Optional[str] = None,
                          max_records: int = 65536) -> Dict:
    """Republish parked records (past the replay cursor) to `target` and
    commit the cursor — at-least-once: the cursor advances only after the
    republish, so a crash mid-replay re-replays rather than losing."""
    target = target or default_replay_target(topic_name, naming)
    consumer = bus.consumer(topic_name, REPLAY_GROUP)
    replayed = 0
    while replayed < max_records:
        batch = consumer.poll(min(4096, max_records - replayed))
        if not batch:
            break
        bus.topic(target).publish_many(
            [(r.key, r.value) for r in batch])
        bus.commit(consumer)
        replayed += len(batch)
    return {"topic": topic_name, "target": target, "replayed": replayed,
            "remaining": _replay_backlog(bus, topic_name)}
