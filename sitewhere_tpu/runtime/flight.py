"""Step flight recorder: per-step stage attribution on one monotonic clock.

Every engine step emits one fixed-shape record — batch lineage id, tenant
mix, event count, and a segment timeline attributing wall time to the
stages of the step path (pack / route / guard / H2D / dispatch /
device-compute / lane-fetch / materialize).  Records are stitched across
the feeder, submitter, and caller threads by *carrying the record object*
through the hand-off structures (`_PreparedStep.flight`, the pipelined
submitter's ready-heap tuples) instead of relying on thread-local span
stacks, which lose parentage at every thread hop.

Hot-path cost is pinned by perf_gate's ``observability_overhead`` check:
recording is lock-free — slots are preallocated, claimed with an atomic
``itertools.count`` ticket, and a mark is two list stores of a
``perf_counter()`` float.  No allocation, dict lookup by string hash only,
and no string formatting until export.

All timestamps share ``time.perf_counter()`` so segments from different
threads are directly comparable: that is what makes
``h2d_overlap_fraction`` (how much of this step's staging-side work ran
while the previous step's dispatch was in flight) computable at export
time without any runtime coordination.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Stage vocabulary — fixed order, fixed index per stage.  The index is
# resolved once at module import; the hot path indexes preallocated
# lists, never touching a dict keyed by a freshly built string.
STAGES: Tuple[str, ...] = (
    "pack",            # host: batch -> wire blob (batch_to_blob)
    "route_host",      # sharded host fallback: arena router route_batch
    "route_device",    # sharded device path: flat-blob pack for radix route
    "guard",           # host: wait on staging-ring transfer guard
    "stage_wait",      # host: backpressure wait for a free staging-ring slot
    "h2d",             # host: device_put submit (async; segment = submit cost)
    "dispatch",        # host: jit step call until handles returned
    "device_compute",  # device: dispatch start -> outputs ready (needs sync)
    "model_eval",      # host: resolve anomaly-model fires from fetched lanes
    "lane_fetch",      # host: the one device_get of the alert+command lanes
    "materialize",     # host: decode lanes + emit alert events
    "actuate",         # host: decode command lanes + resolve policy fires
    "command_fanout",  # host: dispatch resolved commands to destinations
)
_STAGE_INDEX: Dict[str, int] = {name: i for i, name in enumerate(STAGES)}
N_STAGES = len(STAGES)

# Staging-side stages: work that a feeder thread can run ahead while the
# step thread still has the previous step's dispatch in flight.  Overlap
# of these segments with the preceding record's dispatch window is the
# ``h2d_overlap_fraction`` ROADMAP item 2 will be gated on.
# ``stage_wait`` is deliberately NOT here: time spent blocked on a full
# staging ring is backpressure, not productive staging work — counting it
# would inflate the overlap fraction exactly when the ring stalls.
_STAGING_STAGES = ("pack", "route_host", "route_device", "guard", "h2d")


class StepRecord:
    """One preallocated flight-record slot.

    ``begin``/``end`` are fixed-length float lists indexed by stage; a
    negative value means "not recorded".  ``reset`` re-arms the slot for
    reuse without reallocating.
    """

    __slots__ = ("seq", "gen", "engine", "events", "tenant_mix",
                 "begin", "end", "created", "age", "ring", "commands")

    def __init__(self) -> None:
        self.seq = -1            # lineage id (recorder-wide monotonic)
        self.gen = -1            # ring generation (claim ticket)
        self.engine = ""         # engine scope name
        self.events = 0
        self.tenant_mix: Optional[Tuple[int, ...]] = None
        self.begin: List[float] = [-1.0] * N_STAGES
        self.end: List[float] = [-1.0] * N_STAGES
        self.created = 0.0
        # event-age ride-along (runtime/eventage.py): an open AgeSidecar
        # while the batch is in flight, replaced by the closed AgeSummary
        # at materialize — export only reads the closed form
        self.age = None
        # staging-ring snapshot at slot-acquire time: (occupancy, depth),
        # None when the step never touched the ring
        self.ring: Optional[Tuple[int, int]] = None
        # command fires resolved from this step's command lane (actuate
        # stage); drives the detection_to_actuation age edge
        self.commands = 0

    # -- hot path -----------------------------------------------------
    def reset(self, seq: int, gen: int, engine: str) -> None:
        self.seq = seq
        self.gen = gen
        self.engine = engine
        self.events = 0
        self.tenant_mix = None
        b, e = self.begin, self.end
        for i in range(N_STAGES):
            b[i] = -1.0
            e[i] = -1.0
        self.created = time.perf_counter()
        self.age = None
        self.ring = None
        self.commands = 0

    def mark(self, stage: str, t0: float, t1: float) -> None:
        """Record a completed segment from explicit timestamps."""
        i = _STAGE_INDEX[stage]
        self.begin[i] = t0
        self.end[i] = t1

    def begin_stage(self, stage: str) -> None:
        self.begin[_STAGE_INDEX[stage]] = time.perf_counter()

    def end_stage(self, stage: str) -> None:
        self.end[_STAGE_INDEX[stage]] = time.perf_counter()

    # -- cold path (export / tests) -----------------------------------
    def stage_s(self, stage: str) -> float:
        """Duration of one stage in seconds, 0.0 if unrecorded."""
        i = _STAGE_INDEX[stage]
        if self.begin[i] < 0.0 or self.end[i] < 0.0:
            return 0.0
        return max(0.0, self.end[i] - self.begin[i])

    def span_bounds(self) -> Optional[Tuple[float, float]]:
        """(first begin, last end) across recorded segments."""
        first = None
        last = None
        for i in range(N_STAGES):
            if self.begin[i] >= 0.0 and self.end[i] >= 0.0:
                first = self.begin[i] if first is None else min(
                    first, self.begin[i])
                last = self.end[i] if last is None else max(
                    last, self.end[i])
        if first is None or last is None:
            return None
        return first, last

    def export(self) -> Dict:
        """Dict form for the REST endpoint / bench.  Allocates — never
        called from the hot path."""
        stages = {}
        sum_s = 0.0
        crit = ""
        crit_s = -1.0
        for i, name in enumerate(STAGES):
            if self.begin[i] < 0.0 or self.end[i] < 0.0:
                continue
            dur = max(0.0, self.end[i] - self.begin[i])
            stages[name] = {
                "begin_s": self.begin[i],
                "ms": round(dur * 1e3, 6),
            }
            sum_s += dur
            if dur > crit_s:
                crit_s = dur
                crit = name
        bounds = self.span_bounds()
        span_s = (bounds[1] - bounds[0]) if bounds else 0.0
        out = {
            "seq": self.seq,
            "engine": self.engine,
            "events": self.events,
            "stages": stages,
            "sum_ms": round(sum_s * 1e3, 6),
            "span_ms": round(span_s * 1e3, 6),
            "critical_stage": crit,
        }
        if self.tenant_mix is not None:
            out["tenant_mix"] = list(self.tenant_mix)
        if self.ring is not None:
            out["ring"] = {"occupancy": self.ring[0],
                           "depth": self.ring[1]}
        age = self.age
        if age is not None and hasattr(age, "export"):
            exported = age.export()
            if exported.get("count"):
                out["age"] = exported
        return out


class FlightRecorder:
    """Fixed-capacity ring of preallocated :class:`StepRecord` slots.

    ``begin_step`` claims the next slot with an atomic counter ticket
    (``itertools.count`` advances under the GIL without a lock) and
    re-arms it; concurrent writers from feeder/submitter/caller threads
    each hold a distinct slot, so marks never contend.  Export walks the
    ring snapshot-style, tolerating slots being rewritten mid-walk by
    checking the generation ticket before and after the copy.
    """

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = int(capacity)
        self._slots = [StepRecord() for _ in range(self.capacity)]
        self._ticket = itertools.count()
        self._export_lock = threading.Lock()

    # -- hot path -----------------------------------------------------
    def begin_step(self, engine: str = "") -> StepRecord:
        gen = next(self._ticket)
        rec = self._slots[gen % self.capacity]
        rec.reset(seq=gen, gen=gen, engine=engine)
        return rec

    # -- cold path ----------------------------------------------------
    def _stable_records(self, last_n: int) -> List[StepRecord]:
        """Copy out the most recent completed slots, newest last.

        A slot is taken only if its generation ticket is unchanged
        across the copy (it wasn't re-armed mid-read)."""
        # itertools.count cannot be peeked without advancing; take the
        # high-water mark from the slots themselves instead.
        top = max((s.gen for s in self._slots), default=-1)
        out: List[StepRecord] = []
        lo = max(0, top - min(last_n, self.capacity) + 1)
        for gen in range(lo, top + 1):
            slot = self._slots[gen % self.capacity]
            if slot.gen != gen:
                continue
            copy = StepRecord()
            copy.seq = slot.seq
            copy.gen = slot.gen
            copy.engine = slot.engine
            copy.events = slot.events
            copy.tenant_mix = slot.tenant_mix
            copy.begin = list(slot.begin)
            copy.end = list(slot.end)
            copy.created = slot.created
            copy.age = slot.age
            copy.ring = slot.ring
            if slot.gen != gen:  # re-armed while we copied: discard
                continue
            out.append(copy)
        return out

    def export(self, last_n: int = 64) -> Dict:
        """Records + rollups for ``GET /api/instance/flight``."""
        with self._export_lock:
            recs = self._stable_records(last_n)
        records = [r.export() for r in recs]
        return {
            "capacity": self.capacity,
            "count": len(records),
            "stages": list(STAGES),
            "records": records,
            "rollups": self._rollups(recs),
        }

    def _rollups(self, recs: Sequence[StepRecord]) -> Dict:
        """Window aggregates: per-stage occupancy, sum-vs-max decomposed
        sync time, h2d overlap fraction, critical-path histogram."""
        if not recs:
            return {"steps": 0}
        window_lo = None
        window_hi = None
        stage_tot = [0.0] * N_STAGES
        sum_ms: List[float] = []
        max_ms: List[float] = []
        crit_count: Dict[str, int] = {}
        events = 0
        for r in recs:
            bounds = r.span_bounds()
            if bounds is None:
                continue
            window_lo = bounds[0] if window_lo is None else min(
                window_lo, bounds[0])
            window_hi = bounds[1] if window_hi is None else max(
                window_hi, bounds[1])
            rec_sum = 0.0
            rec_max = 0.0
            crit = ""
            for i in range(N_STAGES):
                if r.begin[i] < 0.0 or r.end[i] < 0.0:
                    continue
                dur = max(0.0, r.end[i] - r.begin[i])
                stage_tot[i] += dur
                rec_sum += dur
                if dur > rec_max:
                    rec_max = dur
                    crit = STAGES[i]
            sum_ms.append(rec_sum * 1e3)
            max_ms.append(rec_max * 1e3)
            if crit:
                crit_count[crit] = crit_count.get(crit, 0) + 1
            events += r.events
        if window_lo is None or window_hi is None:
            return {"steps": 0}
        wall = max(window_hi - window_lo, 1e-9)
        occupancy = {
            STAGES[i]: round(stage_tot[i] / wall, 4)
            for i in range(N_STAGES) if stage_tot[i] > 0.0
        }
        n = len(sum_ms)
        # ingest->effect event-age rollup: merge the closed AgeSummary
        # ride-alongs across the window and derive p50/p99 from the log2
        # buckets (runtime/eventage.py) — the flight endpoint's answer to
        # "how old were events when their effects landed"
        age_total = None
        for r in recs:
            age = r.age
            if age is None or not hasattr(age, "buckets") \
                    or not getattr(age, "count", 0):
                continue
            if age_total is None:
                from sitewhere_tpu.runtime.eventage import AgeSummary
                age_total = AgeSummary()
            age_total.merge(age)
        out_age = age_total.export() if age_total is not None else None
        # staging-ring occupancy rollup: how full the H2D ring ran across
        # the window (mean/max of the at-acquire snapshots).  A ring
        # pinned at depth means the feeder is transfer-bound; zero means
        # the ring never engaged (serial path or depth 1 idle).
        ring_occ = [r.ring[0] for r in recs if r.ring is not None]
        ring_depth = max((r.ring[1] for r in recs if r.ring is not None),
                         default=0)
        ring_out = None
        if ring_occ:
            ring_out = {
                "depth": ring_depth,
                "mean_occupancy": round(sum(ring_occ) / len(ring_occ), 3),
                "max_occupancy": max(ring_occ),
            }
        return {
            "steps": n,
            "events": events,
            "window_ms": round(wall * 1e3, 3),
            **({"event_age": out_age} if out_age else {}),
            **({"staging_ring": ring_out} if ring_out else {}),
            "stage_occupancy": occupancy,
            # sum-vs-max: if the pipeline overlapped perfectly, wall per
            # step converges to the max stage cost; serial execution
            # pays the sum.  Both are exported so the ratio is readable.
            "sync_total_ms": {
                "sum_of_stages": round(sum(sum_ms) / n, 4),
                "max_stage": round(sum(max_ms) / n, 4),
            },
            "critical_stage_counts": crit_count,
            "h2d_overlap_fraction": round(
                self._h2d_overlap_fraction(recs), 4),
        }

    @staticmethod
    def _h2d_overlap_fraction(recs: Sequence[StepRecord]) -> float:
        """Fraction of staging-side work (pack/route/guard/h2d) that ran
        while the *previous* record's dispatch window was still open.

        Zero for a serial submit loop; approaches 1.0 when a feeder
        stages batch N+1 entirely under batch N's dispatch.  Computable
        offline because every mark shares one monotonic clock."""
        di = _STAGE_INDEX["dispatch"]
        staging_idx = [_STAGE_INDEX[s] for s in _STAGING_STAGES]
        total = 0.0
        overlapped = 0.0
        by_seq = sorted(recs, key=lambda r: r.seq)
        for prev, cur in zip(by_seq, by_seq[1:]):
            if prev.begin[di] < 0.0 or prev.end[di] < 0.0:
                continue
            d0, d1 = prev.begin[di], prev.end[di]
            for i in staging_idx:
                if cur.begin[i] < 0.0 or cur.end[i] < 0.0:
                    continue
                b, e = cur.begin[i], cur.end[i]
                total += max(0.0, e - b)
                overlapped += max(0.0, min(e, d1) - max(b, d0))
        if total <= 0.0:
            return 0.0
        return min(1.0, overlapped / total)


# Process-wide recorder: engines default to this, the REST endpoint and
# bench read from it.  Mirrors GLOBAL_METRICS / GLOBAL_TRACER.
GLOBAL_FLIGHT = FlightRecorder()
