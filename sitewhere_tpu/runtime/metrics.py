"""Metrics registry: counters, meters (rates), timers with percentiles.

Reference: Dropwizard metrics registry per microservice (Microservice.java:146),
per-component timers/meters created via
TenantEngineLifecycleComponent.createTimerMetric (used on the hot path at
InboundPayloadProcessingLogic.java:76-81). Here: a lock-cheap in-proc registry;
timers keep a bounded reservoir for p50/p95/p99.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional


def _prom_name(name: str) -> str:
    """Metric key -> prometheus-legal name (dots and dashes collapse to
    underscores; leading digits get a prefix)."""
    out = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    return f"m_{out}" if out and out[0].isdigit() else out


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Meter:
    """Event rate: total count + exponentially-weighted 1-minute rate."""

    def __init__(self) -> None:
        self.count = 0
        self._rate = 0.0
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            now = time.monotonic()
            dt = now - self._last
            self.count += n
            if dt > 0:
                inst = n / dt
                alpha = min(1.0, dt / 60.0)
                self._rate += alpha * (inst - self._rate)
                self._last = now

    @property
    def one_minute_rate(self) -> float:
        return self._rate


class Timer:
    """Duration histogram with a sliding reservoir (last `capacity` samples)."""

    def __init__(self, capacity: int = 2048) -> None:
        self._samples: List[float] = []
        self._capacity = capacity
        self._idx = 0
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            if len(self._samples) < self._capacity:
                self._samples.append(seconds)
            else:
                self._samples[self._idx] = seconds
                self._idx = (self._idx + 1) % self._capacity

    class _Ctx:
        def __init__(self, timer: "Timer"):
            self._timer = timer

        def __enter__(self) -> "Timer._Ctx":
            self._start = time.perf_counter()
            return self

        def __exit__(self, *exc) -> None:
            self._timer.update(time.perf_counter() - self._start)

    def time(self) -> "Timer._Ctx":
        return Timer._Ctx(self)

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            ordered = sorted(self._samples)
            k = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
            return ordered[k]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            count, total = self.count, self.total
        return {
            "count": count,
            "mean_s": (total / count) if count else 0.0,
            "p50_s": self.percentile(0.50),
            "p95_s": self.percentile(0.95),
            "p99_s": self.percentile(0.99),
        }


class MetricsRegistry:
    """Named metric registry; names are prefixed by component/tenant scope the
    way TenantEngineLifecycleComponent prefixes metric names."""

    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._meters: Dict[str, Meter] = {}
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def meter(self, name: str) -> Meter:
        with self._lock:
            return self._meters.setdefault(name, Meter())

    def timer(self, name: str) -> Timer:
        with self._lock:
            return self._timers.setdefault(name, Timer())

    def scoped(self, prefix: str) -> "ScopedMetrics":
        return ScopedMetrics(self, prefix)

    def report(self) -> Dict[str, Dict]:
        """Serializable snapshot (reference: Slf4j reporter every 20s)."""
        with self._lock:
            counters = dict(self._counters)
            meters = dict(self._meters)
            timers = dict(self._timers)
        return {
            "counters": {k: v.value for k, v in counters.items()},
            "meters": {k: {"count": v.count, "m1_rate": v.one_minute_rate}
                       for k, v in meters.items()},
            "timers": {k: v.snapshot() for k, v in timers.items()},
        }

    def prometheus_text(self, extra_gauges: Optional[Dict[str, float]] = None
                        ) -> str:
        """Prometheus text exposition (version 0.0.4) of every registered
        metric — the role of the reference's Dropwizard reporters
        (Microservice.java:146,244-246), scrapeable at GET /metrics.
        Counters/meter-counts become prometheus counters, meter 1-minute
        rates and `extra_gauges` become gauges, timers become summaries
        with p50/p95/p99 quantiles."""
        with self._lock:
            counters = dict(self._counters)
            meters = dict(self._meters)
            timers = dict(self._timers)
        lines: List[str] = []

        def emit(name: str, kind: str, value, labels: str = "") -> None:
            if kind:
                lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name}{labels} {value}")

        for key in sorted(counters):
            emit(f"swtpu_{_prom_name(key)}_total", "counter",
                 counters[key].value)
        for key in sorted(meters):
            meter = meters[key]
            base = f"swtpu_{_prom_name(key)}"
            emit(f"{base}_total", "counter", meter.count)
            emit(f"{base}_m1_rate", "gauge",
                 round(meter.one_minute_rate, 6))
        for key in sorted(timers):
            snap = timers[key].snapshot()
            base = f"swtpu_{_prom_name(key)}_seconds"
            lines.append(f"# TYPE {base} summary")
            for quantile in ("p50", "p95", "p99"):
                lines.append(
                    f'{base}{{quantile="0.{quantile[1:]}"}} '
                    f'{snap[f"{quantile}_s"]:.9f}')
            lines.append(f"{base}_count {snap['count']}")
            lines.append(
                f"{base}_sum {snap['mean_s'] * snap['count']:.9f}")
        for key in sorted(extra_gauges or {}):
            emit(f"swtpu_{_prom_name(key)}", "gauge", extra_gauges[key])
        return "\n".join(lines) + "\n"


class ScopedMetrics:
    def __init__(self, registry: MetricsRegistry, prefix: str):
        self._registry = registry
        self._prefix = prefix

    def counter(self, name: str) -> Counter:
        return self._registry.counter(f"{self._prefix}.{name}")

    def meter(self, name: str) -> Meter:
        return self._registry.meter(f"{self._prefix}.{name}")

    def timer(self, name: str) -> Timer:
        return self._registry.timer(f"{self._prefix}.{name}")


GLOBAL_METRICS = MetricsRegistry()
