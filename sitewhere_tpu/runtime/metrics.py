"""Metrics registry: counters, meters (rates), timers with percentiles.

Reference: Dropwizard metrics registry per microservice (Microservice.java:146),
per-component timers/meters created via
TenantEngineLifecycleComponent.createTimerMetric (used on the hot path at
InboundPayloadProcessingLogic.java:76-81). Here: a lock-cheap in-proc registry;
timers keep a bounded reservoir for p50/p95/p99.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional


def _prom_name(name: str) -> str:
    """Metric key -> prometheus-legal name (dots and dashes collapse to
    underscores; leading digits get a prefix)."""
    out = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    return f"m_{out}" if out and out[0].isdigit() else out


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Meter:
    """Event rate: total count + exponentially-weighted 1-minute rate."""

    def __init__(self) -> None:
        self.count = 0
        self._rate = 0.0
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            now = time.monotonic()
            dt = now - self._last
            self.count += n
            if dt > 0:
                inst = n / dt
                alpha = min(1.0, dt / 60.0)
                self._rate += alpha * (inst - self._rate)
                self._last = now

    @property
    def one_minute_rate(self) -> float:
        return self._rate


class Timer:
    """Duration histogram with a sliding reservoir (last `capacity` samples)."""

    def __init__(self, capacity: int = 2048) -> None:
        self._samples: List[float] = []
        self._capacity = capacity
        self._idx = 0
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            if len(self._samples) < self._capacity:
                self._samples.append(seconds)
            else:
                self._samples[self._idx] = seconds
                self._idx = (self._idx + 1) % self._capacity

    class _Ctx:
        def __init__(self, timer: "Timer"):
            self._timer = timer

        def __enter__(self) -> "Timer._Ctx":
            self._start = time.perf_counter()
            return self

        def __exit__(self, *exc) -> None:
            self._timer.update(time.perf_counter() - self._start)

    def time(self) -> "Timer._Ctx":
        return Timer._Ctx(self)

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            ordered = sorted(self._samples)
            k = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
            return ordered[k]

    def snapshot(self) -> Dict[str, float]:
        # One lock acquisition, one sorted copy — percentile() used to be
        # called per quantile, re-locking and re-sorting the reservoir
        # three times per snapshot.
        with self._lock:
            count, total = self.count, self.total
            ordered = sorted(self._samples)

        def pct(q: float) -> float:
            if not ordered:
                return 0.0
            k = min(len(ordered) - 1,
                    max(0, int(round(q * (len(ordered) - 1)))))
            return ordered[k]

        return {
            "count": count,
            "total_s": total,
            "mean_s": (total / count) if count else 0.0,
            "p50_s": pct(0.50),
            "p95_s": pct(0.95),
            "p99_s": pct(0.99),
        }


# Default buckets for step-path latencies (seconds): sub-ms device steps
# through multi-second stalls, roughly logarithmic.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5)

# Cardinality guard: a histogram family never grows past this many
# labeled children. The label vocabularies are meant to be fixed (stage
# names, engine names, bounded tenant indices) — an unbounded label
# (device token, batch id) would grow the exposition without limit, so
# past the cap observations land on a per-family `_overflow` child and
# `metrics.label_overflow` counts the spills (loud, never silent).
MAX_LABEL_CHILDREN = 64


class Histogram:
    """Prometheus-style bucketed histogram with optional labels.

    Unlike :class:`Timer`'s sliding reservoir (whose p50/p95/p99 are
    scrape-time approximations that cannot be aggregated across
    instances), cumulative buckets survive aggregation and let the
    scraper compute any quantile.  Labels (e.g. ``stage=``, ``tenant=``)
    key independent child series: each distinct label set carries its
    own bucket counts, ``_sum`` and ``_count``."""

    class _Child:
        __slots__ = ("counts", "total", "count")

        def __init__(self, n_buckets: int) -> None:
            self.counts = [0] * n_buckets  # cumulative at export, raw here
            self.total = 0.0
            self.count = 0

    def __init__(self, buckets: Optional[tuple] = None,
                 max_children: int = MAX_LABEL_CHILDREN) -> None:
        self.buckets = tuple(buckets if buckets is not None
                             else DEFAULT_BUCKETS)
        self.max_children = max_children
        self._children: Dict[tuple, "Histogram._Child"] = {}
        self._lock = threading.Lock()

    def child(self, **labels: str) -> "Histogram._Child":
        key = tuple(sorted(labels.items()))
        overflowed = False
        with self._lock:
            ch = self._children.get(key)
            if ch is None:
                if key and len(self._children) >= self.max_children:
                    # cardinality cap: spill to the family's _overflow
                    # child (same label keys, sentinel values) instead of
                    # growing the exposition unboundedly
                    key = tuple((lk, "_overflow") for lk, _ in key)
                    ch = self._children.get(key)
                    overflowed = True
                if ch is None:
                    ch = Histogram._Child(len(self.buckets))
                    self._children[key] = ch
        if overflowed:
            # outside self._lock; the registry lock nests independently
            GLOBAL_METRICS.counter("metrics.label_overflow").inc()
        return ch

    def observe(self, seconds: float, **labels: str) -> None:
        ch = self.child(**labels)
        with self._lock:
            ch.total += seconds
            ch.count += 1
            # raw per-bucket counts; cumulated at export so observe is
            # a single increment
            for i, ub in enumerate(self.buckets):
                if seconds <= ub:
                    ch.counts[i] += 1
                    break

    def observe_buckets(self, bucket_counts, sum_value: float, count: int,
                        **labels: str) -> None:
        """Aggregate-observe: fold precomputed raw per-bucket counts in
        one call (the age sidecar closes a whole batch this way — never
        a per-event observe loop on the hot path). The first
        ``len(self.buckets)`` entries align with ``self.buckets``; any
        trailing entries count only toward ``_count`` (the +Inf
        bucket)."""
        ch = self.child(**labels)
        with self._lock:
            ch.total += sum_value
            ch.count += count
            counts = ch.counts
            n = len(counts)
            for i, c in enumerate(bucket_counts):
                if c and i < n:
                    counts[i] += c

    def snapshot(self) -> Dict:
        with self._lock:
            out = {}
            for key, ch in self._children.items():
                cum = []
                running = 0
                for c in ch.counts:
                    running += c
                    cum.append(running)
                out[key] = {"buckets": cum, "sum_s": ch.total,
                            "count": ch.count}
            return out


class MetricsRegistry:
    """Named metric registry; names are prefixed by component/tenant scope the
    way TenantEngineLifecycleComponent prefixes metric names."""

    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._meters: Dict[str, Meter] = {}
        self._timers: Dict[str, Timer] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def meter(self, name: str) -> Meter:
        with self._lock:
            return self._meters.setdefault(name, Meter())

    def timer(self, name: str) -> Timer:
        with self._lock:
            return self._timers.setdefault(name, Timer())

    def histogram(self, name: str,
                  buckets: Optional[tuple] = None) -> Histogram:
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = Histogram(buckets)
                self._histograms[name] = hist
            return hist

    def scoped(self, prefix: str) -> "ScopedMetrics":
        return ScopedMetrics(self, prefix)

    def report(self) -> Dict[str, Dict]:
        """Serializable snapshot (reference: Slf4j reporter every 20s)."""
        with self._lock:
            counters = dict(self._counters)
            meters = dict(self._meters)
            timers = dict(self._timers)
            histograms = dict(self._histograms)
        return {
            "counters": {k: v.value for k, v in counters.items()},
            "meters": {k: {"count": v.count, "m1_rate": v.one_minute_rate}
                       for k, v in meters.items()},
            "timers": {k: v.snapshot() for k, v in timers.items()},
            "histograms": {
                k: {"&".join(f"{lk}={lv}" for lk, lv in key) or "_": snap
                    for key, snap in v.snapshot().items()}
                for k, v in histograms.items()},
        }

    def prometheus_text(self, extra_gauges: Optional[Dict[str, float]] = None
                        ) -> str:
        """Prometheus text exposition (version 0.0.4) of every registered
        metric — the role of the reference's Dropwizard reporters
        (Microservice.java:146,244-246), scrapeable at GET /metrics.
        Counters/meter-counts become prometheus counters, meter 1-minute
        rates and `extra_gauges` become gauges, timers become summaries
        with p50/p95/p99 quantiles."""
        with self._lock:
            counters = dict(self._counters)
            meters = dict(self._meters)
            timers = dict(self._timers)
            histograms = dict(self._histograms)
        lines: List[str] = []

        def emit(name: str, kind: str, value, labels: str = "") -> None:
            if kind:
                lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name}{labels} {value}")

        for key in sorted(counters):
            emit(f"swtpu_{_prom_name(key)}_total", "counter",
                 counters[key].value)
        for key in sorted(meters):
            meter = meters[key]
            base = f"swtpu_{_prom_name(key)}"
            emit(f"{base}_total", "counter", meter.count)
            emit(f"{base}_m1_rate", "gauge",
                 round(meter.one_minute_rate, 6))
        for key in sorted(timers):
            snap = timers[key].snapshot()
            base = f"swtpu_{_prom_name(key)}_seconds"
            lines.append(f"# TYPE {base} summary")
            for quantile in ("p50", "p95", "p99"):
                lines.append(
                    f'{base}{{quantile="0.{quantile[1:]}"}} '
                    f'{snap[f"{quantile}_s"]:.9f}')
            lines.append(f"{base}_count {snap['count']}")
            # true accumulated total, not the lossy mean*count round-trip
            lines.append(f"{base}_sum {snap['total_s']:.9f}")
        for key in sorted(histograms):
            hist = histograms[key]
            # histograms carry their unit in the registry name
            # (step_stage_seconds, step_tenant_events) — no blanket
            # _seconds suffix like the duration-only timers get
            base = f"swtpu_{_prom_name(key)}"
            lines.append(f"# TYPE {base} histogram")
            for labelkey, snap in sorted(hist.snapshot().items()):
                label_pairs = [
                    f'{_prom_name(lk)}="{lv}"' for lk, lv in labelkey]
                prefix = ",".join(label_pairs)
                sep = "," if prefix else ""
                for ub, cum in zip(hist.buckets, snap["buckets"]):
                    lines.append(
                        f'{base}_bucket{{{prefix}{sep}le="{ub:g}"}} {cum}')
                lines.append(
                    f'{base}_bucket{{{prefix}{sep}le="+Inf"}} '
                    f'{snap["count"]}')
                lbl = f"{{{prefix}}}" if prefix else ""
                lines.append(f'{base}_sum{lbl} {snap["sum_s"]:.9f}')
                lines.append(f'{base}_count{lbl} {snap["count"]}')
        # extra gauges may carry a literal label block in the key
        # (`hbm.table_bytes{table="device_state"}`): one TYPE line per
        # family, labels pass through verbatim
        extras = extra_gauges or {}
        typed: set = set()
        for key in sorted(extras):
            name, brace, labelrest = key.partition("{")
            base = f"swtpu_{_prom_name(name)}"
            if base not in typed:
                lines.append(f"# TYPE {base} gauge")
                typed.add(base)
            labels = ("{" + labelrest) if brace else ""
            lines.append(f"{base}{labels} {extras[key]}")
        return "\n".join(lines) + "\n"


class ScopedMetrics:
    def __init__(self, registry: MetricsRegistry, prefix: str):
        self._registry = registry
        self._prefix = prefix

    def counter(self, name: str) -> Counter:
        return self._registry.counter(f"{self._prefix}.{name}")

    def meter(self, name: str) -> Meter:
        return self._registry.meter(f"{self._prefix}.{name}")

    def timer(self, name: str) -> Timer:
        return self._registry.timer(f"{self._prefix}.{name}")

    def histogram(self, name: str,
                  buckets: Optional[tuple] = None) -> Histogram:
        return self._registry.histogram(f"{self._prefix}.{name}", buckets)


GLOBAL_METRICS = MetricsRegistry()
