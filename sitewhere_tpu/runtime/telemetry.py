"""Opt-in instance usage telemetry — the MicroserviceAnalytics role.

Reference: every microservice reports lifecycle analytics — Started /
Uptime / Stopped events carrying the service identifier and version
(sitewhere-microservice MicroserviceAnalytics.java:39-77, wired to a
hard-coded Google Analytics tracking id and always on). The rebuild
keeps the capability but inverts the defaults the privacy-correct way:
OFF unless configured, and events post to the OPERATOR'S OWN endpoint
(`telemetry.endpoint`), never a third party. Payloads are lifecycle
metadata only (instance id, version, event, uptime seconds) — no device
data, no tenant data.

Config (runtime/config.py `telemetry.*`): `enabled` (default false),
`endpoint` (required when enabled), `interval_s` (uptime heartbeat
cadence, default 3600). Failures are logged at debug and never affect
the instance — telemetry is strictly best-effort, like the reference's
catch-all `warn` swallow.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
import urllib.request
from typing import Optional

LOGGER = logging.getLogger("sitewhere.telemetry")


class UsageTelemetry:
    """Posts Started/Uptime/Stopped lifecycle events to a configured
    HTTP endpoint as JSON (one POST per event).

    Every POST happens on a single background worker thread — a slow or
    blackholed endpoint never sits on the boot thread (start() only
    enqueues) or the SIGTERM path (stop() enqueues the final event and
    bounds its wait; the daemon worker is abandoned past the bound)."""

    _STOP = object()

    def __init__(self, endpoint: str, instance_id: str, version: str,
                 interval_s: float = 3600.0, timeout_s: float = 5.0):
        self.endpoint = endpoint
        self.instance_id = instance_id
        self.version = version
        self.interval_s = float(interval_s)
        self.timeout_s = timeout_s
        self._started_at: Optional[float] = None
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._started_at = time.monotonic()
        self._thread = threading.Thread(target=self._run,
                                        name="usage-telemetry", daemon=True)
        self._thread.start()
        self._queue.put("started")

    def stop(self) -> None:
        if self._thread is None:
            return
        self._queue.put("stopped")
        self._queue.put(self._STOP)
        # bounded: at worst one in-flight POST + the stopped POST; a
        # wedged endpoint abandons the daemon worker rather than holding
        # shutdown hostage
        self._thread.join(timeout=2 * self.timeout_s + 1)
        self._thread = None

    def _run(self) -> None:
        # Explicit next-heartbeat deadline: `get(timeout=interval_s)`
        # alone restarts the countdown on every enqueued event, so a
        # steady event stream silences the uptime heartbeat entirely.
        deadline = time.monotonic() + self.interval_s
        while True:
            wait = deadline - time.monotonic()
            if wait <= 0.0:
                self._send("uptime")
                deadline = time.monotonic() + self.interval_s
                continue
            try:
                item = self._queue.get(timeout=wait)
            except queue.Empty:
                self._send("uptime")
                deadline = time.monotonic() + self.interval_s
                continue
            if item is self._STOP:
                return
            self._send(item)

    # -- transport ---------------------------------------------------------
    def _send(self, event: str) -> None:
        uptime = (time.monotonic() - self._started_at
                  if self._started_at is not None else 0.0)
        payload = json.dumps({
            "instance": self.instance_id,
            "version": self.version,
            "event": event,
            "uptime_s": round(uptime, 1),
        }).encode("utf-8")
        request = urllib.request.Request(
            self.endpoint, data=payload,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(request,
                                        timeout=self.timeout_s) as rsp:
                rsp.read()
        except Exception as err:  # noqa: BLE001 — strictly best-effort:
            # nothing an endpoint does (URLError, BadStatusLine, bad
            # content) may ever affect the instance or kill this worker
            # (MicroserviceAnalytics swallows Throwable the same way)
            LOGGER.debug("usage telemetry %s not delivered: %s", event, err)


def build_from_config(cfg, instance_id: str) -> Optional[UsageTelemetry]:
    """UsageTelemetry when `telemetry.enabled` AND an endpoint is set;
    None otherwise (the default: no phone-home of any kind)."""
    if not cfg.get("telemetry.enabled"):
        return None
    endpoint = cfg.get("telemetry.endpoint")
    if not endpoint:
        LOGGER.warning("telemetry.enabled set without telemetry.endpoint; "
                       "usage telemetry stays off")
        return None
    import sitewhere_tpu

    return UsageTelemetry(
        endpoint=endpoint, instance_id=instance_id,
        version=sitewhere_tpu.__version__,
        interval_s=float(cfg.get("telemetry.interval_s") or 3600.0))
