"""Partitioned event bus: the in-process data plane replacing Kafka.

Reference: the Kafka topic pipeline (SURVEY.md §1) — topics named
`{product}.{instance}.tenant.{tenant}.{suffix}` (KafkaTopicNaming.java:81-98),
per-key partitioning for per-device ordering, consumer groups with committed
offsets (MicroserviceKafkaConsumer.java:36, offset commit in
DecodedEventsConsumer.java:194-199), at-least-once delivery, and replay.

Here a Topic is N append-only partitions. Records are (offset, key, value)
byte pairs; a record's partition is hash(key) % N, preserving per-device
ordering exactly like the reference's device-token record keys. Consumer
groups track committed offsets per partition and independently replay.
Durability is an optional length-prefixed append log per partition, replayed
on open — the Kafka-replay story the device-state cache depends on
(SURVEY.md §5 checkpoint/resume) works the same way here.

TPU note: the hot path deliberately does NOT hop through this bus between
stages the way the reference hops through Kafka between microservices — the
fused pjit step (pipeline/step.py) replaces those broker round-trips. The bus
carries the pod-edge flows: ingest -> pipeline, pipeline -> outbound
connectors / command delivery, plus control-plane topics.
"""

from __future__ import annotations

import os
import random
import struct
import threading
import time
import zlib

from typing import Callable, Dict, Iterator, List, NamedTuple, Optional, Tuple


class Record(NamedTuple):
    """One bus record. A NamedTuple, not a frozen dataclass: poll paths
    construct hundreds of thousands per second and frozen-dataclass
    __init__ (object.__setattr__ per field) dominated networked-poll
    profiles."""

    topic: str
    partition: int
    offset: int
    key: bytes
    value: bytes
    timestamp_ms: int


def batch_extent(records: List["Record"]) -> Dict[int, int]:
    """Per-partition exclusive end offsets of a polled batch — the extent
    retry cycles re-poll (ConsumerHost / RemoteConsumerHost `until`)."""
    extent: Dict[int, int] = {}
    for record in records:
        extent[record.partition] = max(extent.get(record.partition, 0),
                                       record.offset + 1)
    return extent


def jittered(backoff_s: float) -> float:
    """Equal-jitter a retry backoff into [backoff/2, backoff]. Without
    this, every consumer of a bounced bus computes the identical
    0.05s-doubling schedule and retries in lockstep — a thundering herd
    on exactly the component trying to come back. Equal (not full)
    jitter keeps a floor of half the deterministic backoff, so retry
    budgets still span roughly the documented total window."""
    return backoff_s * (0.5 + 0.5 * random.random())


class TopicNaming:
    """Topic name taxonomy (KafkaTopicNaming.java:33-98)."""

    def __init__(self, product: str = "swtpu", instance: str = "default"):
        self.product = product
        self.instance = instance

    def _global(self, suffix: str) -> str:
        return f"{self.product}.{self.instance}.{suffix}"

    def _tenant(self, tenant: str, suffix: str) -> str:
        return f"{self.product}.{self.instance}.tenant.{tenant}.{suffix}"

    # global topics (KafkaTopicNaming.java:33-43)
    def microservice_state_updates(self) -> str:
        return self._global("microservice-state-updates")

    def instance_topology_updates(self) -> str:
        return self._global("instance-topology-updates")

    def tenant_model_updates(self) -> str:
        return self._global("tenant-model-updates")

    def provisioning_model_updates(self) -> str:
        """Cross-host control-plane provisioning stream (tenant/user/
        authority mutations, multitenant/replication.py) — the cluster
        analog of the per-host tenant-model-updates topic."""
        return self._global("provisioning-model-updates")

    def instance_logging(self) -> str:
        return self._global("instance-logging")

    def feeder_frames(self) -> str:
        """Raw hot-event wire frames awaiting a feeder's decode+pack
        (feeders/): partition ownership follows TTL leases, not consumer
        membership, so this stays a global topic — tenancy is resolved by
        the engine after the blob lands."""
        return self._global("feeder-frames")

    # per-tenant topics (KafkaTopicNaming.java:45-69)
    def event_source_decoded_events(self, tenant: str) -> str:
        return self._tenant(tenant, "event-source-decoded-events")

    def event_source_failed_decode_events(self, tenant: str) -> str:
        return self._tenant(tenant, "event-source-failed-decode-events")

    def inbound_persisted_events(self, tenant: str) -> str:
        return self._tenant(tenant, "inbound-persisted-events")

    def inbound_enriched_events(self, tenant: str) -> str:
        return self._tenant(tenant, "inbound-enriched-events")

    def inbound_enriched_batches(self, tenant: str) -> str:
        """Batch-granularity enriched stream for the bulk lane: one compact
        marker per persisted EventBatch (tenant, row count, event-date
        span) instead of one envelope per event — consumers read the
        referenced rows back from the columnar log. The per-event
        `inbound_enriched_events` topic stays the control-plane-rate
        surface; no per-event Python object survives the bulk path."""
        return self._tenant(tenant, "inbound-enriched-batches")

    def inbound_enriched_command_invocations(self, tenant: str) -> str:
        return self._tenant(tenant, "inbound-enriched-command-invocations")

    def inbound_device_registration_events(self, tenant: str) -> str:
        return self._tenant(tenant, "inbound-device-registration-events")

    def inbound_unregistered_device_events(self, tenant: str) -> str:
        return self._tenant(tenant, "inbound-unregistered-device-events")

    def inbound_reprocess_events(self, tenant: str) -> str:
        return self._tenant(tenant, "inbound-reprocess-events")

    def undelivered_command_invocations(self, tenant: str) -> str:
        return self._tenant(tenant, "undelivered-command-invocations")


_FRAME = struct.Struct("<IIq")  # key_len, value_len, timestamp_ms


class _Partition:
    """One append-only ordered log. Thread-safe; optionally file-backed."""

    def __init__(self, path: Optional[str] = None):
        self._records: List[Tuple[int, bytes, bytes, int]] = []  # offset, k, v, ts
        self._base_offset = 0  # offset of _records[0] after truncation
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._fh = None
        if path:
            self._load(path)
            self._fh = open(path, "ab")

    def _load(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as fh:
            data = fh.read()
        pos, offset = 0, 0
        while pos + _FRAME.size <= len(data):
            klen, vlen, ts = _FRAME.unpack_from(data, pos)
            pos += _FRAME.size
            if pos + klen + vlen > len(data):
                break  # torn tail write; drop
            key = data[pos:pos + klen]
            value = data[pos + klen:pos + klen + vlen]
            pos += klen + vlen
            self._records.append((offset, key, value, ts))
            offset += 1

    def append(self, key: bytes, value: bytes) -> int:
        ts = int(time.time() * 1000)
        with self._cv:
            offset = self._base_offset + len(self._records)
            self._records.append((offset, key, value, ts))
            if self._fh is not None:
                self._fh.write(_FRAME.pack(len(key), len(value), ts))
                self._fh.write(key)
                self._fh.write(value)
                # flush to the OS page cache: an accepted record must
                # survive a process crash (Kafka's default durability —
                # page cache, not fsync). Without this, records sat in
                # userspace buffers and a crash lost events producers
                # thought were accepted.
                self._fh.flush()
            self._cv.notify_all()
            return offset

    def append_many(self, records: List[Tuple[bytes, bytes]]) -> int:
        """Bulk append under ONE lock acquisition / durable write / wakeup
        (the per-record path costs a lock+notify each — the networked bus
        edge moves thousands of records per request). Returns the offset
        of the LAST appended record."""
        ts = int(time.time() * 1000)
        with self._cv:
            offset = self._base_offset + len(self._records) - 1
            chunks: List[bytes] = []
            for key, value in records:
                offset += 1
                self._records.append((offset, key, value, ts))
                if self._fh is not None:
                    chunks.append(_FRAME.pack(len(key), len(value), ts))
                    chunks.append(key)
                    chunks.append(value)
            if self._fh is not None and chunks:
                self._fh.write(b"".join(chunks))
                self._fh.flush()  # page-cache durability, once per batch
            self._cv.notify_all()
            return offset

    def read(self, from_offset: int, max_records: int) -> List[Tuple[int, bytes, bytes, int]]:
        with self._lock:
            start = max(0, from_offset - self._base_offset)
            return self._records[start:start + max_records]

    def end_offset(self) -> int:
        with self._lock:
            return self._base_offset + len(self._records)

    def start_offset(self) -> int:
        with self._lock:
            return self._base_offset

    def truncate_before(self, offset: int) -> None:
        """Drop in-memory records below `offset` (retention)."""
        with self._lock:
            drop = offset - self._base_offset
            if drop > 0:
                del self._records[:drop]
                self._base_offset = offset

    def wait_for_data(self, from_offset: int, timeout_s: float) -> bool:
        with self._cv:
            if self._base_offset + len(self._records) > from_offset:
                return True
            self._cv.wait(timeout_s)
            return self._base_offset + len(self._records) > from_offset

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class Topic:
    # sweep cadence: retention is evaluated per partition once per this
    # many appends (amortizes the group-floor scan off the hot path)
    RETENTION_CHECK_EVERY = 2048

    def __init__(self, name: str, partitions: int, data_dir: Optional[str] = None):
        self.name = name
        paths = [None] * partitions
        if data_dir:
            safe = name.replace("/", "_")
            topic_dir = os.path.join(data_dir, safe)
            os.makedirs(topic_dir, exist_ok=True)
            paths = [os.path.join(topic_dir, f"p{i:04d}.log") for i in range(partitions)]
        self.partitions = [_Partition(p) for p in paths]
        # in-memory retention (Kafka's log.retention role, bounded RAM):
        # installed by EventBus.enable_retention() AFTER boot replay —
        # None = unlimited (standalone topics, pre-restore boot window)
        self._retention_records: Optional[int] = None
        self._floor_fn = None           # partition idx -> min committed
        self._since_check = [0] * partitions
        self.retention_dropped = 0

    def enable_retention(self, max_records: int, floor_fn) -> None:
        self._retention_records = int(max_records)
        self._floor_fn = floor_fn
        for idx in range(len(self.partitions)):
            self._apply_retention(idx)

    def _apply_retention(self, idx: int) -> None:
        """Truncate partition `idx`'s in-memory window. Keeps, from
        newest to oldest: the cap window (future/new consumers can read
        that far back, like Kafka's retention window); anything an
        EXISTING group has not committed yet (crash-replay stays intact
        for live laggards); but never more than 8x the cap — a dead
        group must not pin unbounded memory (Kafka answers the same way:
        retention wins over a too-slow consumer; the busnet consumer
        path already handles truncated extents)."""
        cap = self._retention_records
        if cap is None:
            return
        p = self.partitions[idx]
        end = p.end_offset()
        cutoff = end - cap
        if cutoff <= p.start_offset():
            return
        floor = self._floor_fn(idx) if self._floor_fn is not None else end
        cutoff = min(cutoff, floor)
        cutoff = max(cutoff, end - 8 * cap)
        if cutoff > p.start_offset():
            self.retention_dropped += cutoff - p.start_offset()
            p.truncate_before(cutoff)

    def _maybe_retain(self, idx: int, appended: int) -> None:
        if self._retention_records is None:
            return
        self._since_check[idx] += appended
        if self._since_check[idx] >= self.RETENTION_CHECK_EVERY:
            self._since_check[idx] = 0
            self._apply_retention(idx)

    def partition_for(self, key: bytes) -> int:
        # Stable across processes/restarts (unlike Python hash()).
        return zlib.crc32(key) % len(self.partitions)

    def publish(self, key: bytes, value: bytes) -> Tuple[int, int]:
        part = self.partition_for(key)
        offset = self.partitions[part].append(key, value)
        self._maybe_retain(part, 1)
        return part, offset

    def publish_many(self, records: List[Tuple[bytes, bytes]]
                     ) -> Tuple[int, int]:
        """Bulk publish: group by partition once, one append_many per
        touched partition. Per-key partition routing (and therefore
        per-device ordering) is identical to publish(). Returns
        (partition, offset) of the LAST record in arrival order."""
        if not records:
            raise ValueError("publish_many requires at least one record")
        by_part: Dict[int, List[Tuple[bytes, bytes]]] = {}
        last_part = 0
        for key, value in records:
            last_part = self.partition_for(key)
            by_part.setdefault(last_part, []).append((key, value))
        last: Tuple[int, int] = (last_part, -1)
        for part, recs in by_part.items():
            offset = self.partitions[part].append_many(recs)
            self._maybe_retain(part, len(recs))
            if part == last_part:
                last = (part, offset)
        return last

    def end_offsets(self) -> List[int]:
        return [p.end_offset() for p in self.partitions]

    def flush(self) -> None:
        for p in self.partitions:
            p.flush()

    def close(self) -> None:
        for p in self.partitions:
            p.close()


class ConsumerGroup:
    """Committed-offset cursor over all partitions of a topic.

    poll() returns the next batch past the *position* (not yet committed);
    commit() advances the committed offsets — crash/restart replays anything
    uncommitted, giving at-least-once semantics like the reference's manual
    offset commits.
    """

    def __init__(self, topic: Topic, group_id: str,
                 committed: Optional[List[int]] = None):
        self.topic = topic
        self.group_id = group_id
        n = len(topic.partitions)
        self.committed = list(committed) if committed else [0] * n
        if len(self.committed) != n:
            self.committed = (self.committed + [0] * n)[:n]
        self.position = list(self.committed)
        self._lock = threading.Lock()
        # records retention truncated AWAY FROM THIS GROUP before it
        # polled them (position < partition base): poll() counts them
        # here instead of silently clamping — a lagging consumer can see
        # exactly how many records it lost, per partition
        self.retention_skipped = 0
        self.retention_skipped_by_partition: Dict[int, int] = {}

    def poll(self, max_records: int = 4096, timeout_s: float = 0.0,
             partitions: Optional[List[int]] = None,
             until: Optional[Dict[int, int]] = None) -> List[Record]:
        """`partitions` restricts the poll to a subset (consumer-group
        member assignment — busnet's networked groups); None = all.
        `until` maps partition -> exclusive end offset and bounds the poll
        to exactly a previously-seen extent (retry cycles re-polling a
        failing batch — records beyond the extent are neither returned nor
        skipped); partitions absent from `until` are not read at all, and
        the long-poll wait is skipped (the bounded rows already exist)."""
        out: List[Record] = []
        owned = (range(len(self.topic.partitions)) if partitions is None
                 else partitions)
        if until is not None:
            owned = [idx for idx in owned if idx in until]
        with self._lock:
            budget = max_records
            for idx in owned:
                if budget <= 0:
                    break
                part = self.topic.partitions[idx]
                base = part.start_offset()
                if self.position[idx] < base:
                    # retention truncated records this group never saw:
                    # surface the skip instead of silently reading from
                    # the new base. Committed advances with the clamp —
                    # the records are gone, a later seek_to_committed
                    # must not re-count (or appear to re-deliver) them.
                    lost = base - self.position[idx]
                    self.retention_skipped += lost
                    self.retention_skipped_by_partition[idx] = (
                        self.retention_skipped_by_partition.get(idx, 0)
                        + lost)
                    self.position[idx] = base
                    self.committed[idx] = max(self.committed[idx], base)
                rows = part.read(self.position[idx], budget)
                if until is not None:
                    rows = [r for r in rows if r[0] < until[idx]]
                for offset, key, value, ts in rows:
                    out.append(Record(self.topic.name, idx, offset, key, value, ts))
                if rows:
                    self.position[idx] = rows[-1][0] + 1
                    budget -= len(rows)
        if not out and timeout_s > 0 and until is None:
            # Deadline-based wait ACROSS partitions: waiting the full
            # timeout on each partition in turn would block a
            # multi-partition idle topic for partitions * timeout (a
            # remote long-poll would outlive its client's socket timeout).
            deadline = time.monotonic() + timeout_s
            if not owned:
                # a member that owns no partitions (more members than
                # partitions) must idle-wait, not busy-spin
                time.sleep(timeout_s)
                return []
            while True:
                for idx in owned:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return []
                    part = self.topic.partitions[idx]
                    if part.wait_for_data(self.position[idx],
                                          min(remaining, 0.05)):
                        return self.poll(max_records, 0.0,
                                         partitions=partitions)
        return out

    def commit(self, partitions: Optional[List[int]] = None) -> None:
        with self._lock:
            if partitions is None:
                self.committed = list(self.position)
            else:
                for idx in partitions:
                    self.committed[idx] = self.position[idx]

    def commit_at(self, offsets: Dict[int, int],
                  partitions: Optional[List[int]] = None) -> None:
        """Commit EXPLICIT per-partition exclusive end offsets (Kafka's
        commitSync(offsets) shape) — the cursor a consumer actually
        finished, independent of where the poll position has since moved.
        Monotonic: never rewinds a committed offset. `partitions`
        restricts the commit to an owned subset (networked groups)."""
        with self._lock:
            for idx, off in offsets.items():
                if partitions is not None and idx not in partitions:
                    continue
                if not 0 <= idx < len(self.committed):
                    continue
                # clamp to the real log end: a buggy/corrupted client
                # extent must never commit past records that don't exist
                # yet (that would silently skip future deliveries — the
                # contract here is "duplicates possible, loss not")
                end = self.topic.partitions[idx].end_offset()
                off = max(0, min(int(off), end))
                self.committed[idx] = max(self.committed[idx], off)
                # preserve the position >= committed invariant, or a
                # reconnect-triggered seek would redeliver (and possibly
                # dead-letter) records this very call just committed
                self.position[idx] = max(self.position[idx],
                                         self.committed[idx])

    def seek_to_committed(self, partitions: Optional[List[int]] = None) -> None:
        with self._lock:
            if partitions is None:
                self.position = list(self.committed)
            else:
                for idx in partitions:
                    self.position[idx] = self.committed[idx]

    def seek_to_beginning(self) -> None:
        with self._lock:
            self.position = [p.start_offset() for p in self.topic.partitions]
            self.committed = list(self.position)

    def lag(self) -> int:
        with self._lock:
            return sum(e - c for e, c in zip(self.topic.end_offsets(), self.committed))


class EventBus:
    """Broker facade: topic registry + consumer-group registry + offsets store.

    Committed group offsets persist to `<data_dir>/_offsets/<topic>@<group>`
    so restart resumes from the last commit (the reference relies on Kafka's
    __consumer_offsets for the same thing).
    """

    def __init__(self, partitions: int = 8, data_dir: Optional[str] = None):
        self._partitions = partitions
        self._data_dir = data_dir
        self._topics: Dict[str, Topic] = {}
        self._groups: Dict[Tuple[str, str], ConsumerGroup] = {}
        self._lock = threading.RLock()  # consumer() -> topic() re-enters
        self._retention_records: Optional[int] = None
        if data_dir:
            os.makedirs(os.path.join(data_dir, "_offsets"), exist_ok=True)

    def enable_retention(self, max_records: int = 65536) -> None:
        """Bound every partition's IN-MEMORY window (Kafka's
        log.retention role). Must be called AFTER boot replay / any
        checkpoint cursor rewind: from then on, a partition keeps its
        newest `max_records` plus whatever live consumer groups have not
        committed (hard-bounded at 8x — see Topic._apply_retention).
        Durable log files are unaffected; in-memory reads below the
        window report a truncated extent, which consumers already
        handle. Applies to existing topics immediately and to topics
        created later."""
        with self._lock:
            self._retention_records = int(max_records)
            topics = list(self._topics.values())
        for topic in topics:
            topic.enable_retention(self._retention_records,
                                   self._floor_fn(topic.name))

    def _floor_fn(self, topic_name: str):
        def floor(idx: int) -> int:
            with self._lock:
                groups = [g for (t, _gid), g in self._groups.items()
                          if t == topic_name]
            floors = []
            for group in groups:
                with group._lock:
                    if idx < len(group.committed):
                        floors.append(group.committed[idx])
            return min(floors) if floors else (1 << 62)
        return floor

    def topic(self, name: str, partitions: Optional[int] = None) -> Topic:
        with self._lock:
            if name not in self._topics:
                topic = Topic(name, partitions or self._partitions,
                              self._data_dir)
                if self._retention_records is not None:
                    topic.enable_retention(self._retention_records,
                                           self._floor_fn(name))
                self._topics[name] = topic
            return self._topics[name]

    def publish(self, topic_name: str, key: bytes, value: bytes) -> Tuple[int, int]:
        return self.topic(topic_name).publish(key, value)

    def publish_batch(self, topic_name: str,
                      records: List[Tuple[bytes, bytes]]) -> Tuple[int, int]:
        """Bulk publish (one lock/write/wakeup per touched partition);
        returns (partition, offset) of the last record."""
        return self.topic(topic_name).publish_many(records)

    def _offsets_path(self, topic_name: str, group_id: str) -> Optional[str]:
        if not self._data_dir:
            return None
        safe = f"{topic_name}@{group_id}".replace("/", "_")
        return os.path.join(self._data_dir, "_offsets", safe)

    def consumer(self, topic_name: str, group_id: str) -> ConsumerGroup:
        with self._lock:
            key = (topic_name, group_id)
            if key not in self._groups:
                committed = None
                path = self._offsets_path(topic_name, group_id)
                if path and os.path.exists(path):
                    with open(path, "r", encoding="utf-8") as fh:
                        committed = [int(x) for x in fh.read().split()] or None
                self._groups[key] = ConsumerGroup(self.topic(topic_name), group_id,
                                                  committed)
            return self._groups[key]

    def _persist_offsets(self, group: ConsumerGroup) -> None:
        path = self._offsets_path(group.topic.name, group.group_id)
        if path:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(" ".join(str(o) for o in group.committed))
            os.replace(tmp, path)

    def commit_at(self, group: ConsumerGroup, offsets: Dict[int, int],
                  partitions: Optional[List[int]] = None) -> None:
        """Explicit-offset commit, persisted like commit()."""
        group.commit_at(offsets, partitions)
        self._persist_offsets(group)

    def commit(self, group: ConsumerGroup,
               partitions: Optional[List[int]] = None) -> None:
        group.commit(partitions)
        self._persist_offsets(group)

    def persisted_topics(self) -> List[str]:
        """Topic names with on-disk logs from ANY process incarnation.
        `topics()` lists only lazily-created in-memory topics — after a
        restart, a durable topic (e.g. parked dead-letter records) exists
        on disk but not in memory until first touch, and the dead-letter
        operability surface must still find it. Names containing '/' are
        stored escaped ('_') and cannot be recovered from the dir listing;
        no framework topic uses '/'."""
        if not self._data_dir or not os.path.isdir(self._data_dir):
            return []
        return [name for name in os.listdir(self._data_dir)
                if name != "_offsets"
                and os.path.isdir(os.path.join(self._data_dir, name))]

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(self._topics)

    def flush(self) -> None:
        with self._lock:
            topics = list(self._topics.values())
        for t in topics:
            t.flush()

    def close(self) -> None:
        with self._lock:
            topics = list(self._topics.values())
            self._topics.clear()
        for t in topics:
            t.close()


class ConsumerHost:
    """Background poll loop driving a handler with batches — the reference's
    MicroserviceKafkaConsumer single-thread poll loop (:115-121) as a
    lifecycle-managed thread. Handler exceptions leave offsets uncommitted so
    the batch redelivers — but only `max_retries` times, with exponential
    backoff between attempts (0.05s doubling to `max_backoff_s`, ~2 min
    total at the defaults) so transient downstream outages are ridden out;
    a batch still failing after that is treated as deterministically
    poisonous, parks on the dead-letter topic, and offsets advance instead
    of redelivering forever. The reference parks failures the same way
    (failed-decode / undelivered topics, KafkaTopicNaming.java:48,69)."""

    def __init__(self, bus: EventBus, topic_name: str, group_id: str,
                 handler: Callable[[List[Record]], None],
                 max_records: int = 4096, poll_timeout_s: float = 0.2,
                 max_retries: int = 12, max_backoff_s: float = 30.0,
                 dead_letter_topic: Optional[str] = None):
        self._bus = bus
        self._topic_name = topic_name
        self._group_id = group_id
        self._handler = handler
        self._max_records = max_records
        self._poll_timeout_s = poll_timeout_s
        self._max_retries = max_retries
        self._max_backoff_s = max_backoff_s
        self.dead_letter_topic = (dead_letter_topic
                                  or f"{topic_name}.dead-letter")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.errors = 0
        self.dead_lettered = 0
        # (committed-offset fingerprint, consecutive failures,
        # per-partition exclusive end offsets of the batch at first
        # failure) — retries re-poll exactly that extent
        self._failing: Optional[
            Tuple[Tuple[int, ...], int, Dict[int, int]]] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"consumer-{self._group_id}", daemon=True)
        self._thread.start()

    def _park(self, batch: List[Record]) -> None:
        """Publish a poisonous batch to the dead-letter topic; caller then
        commits past it. Key/value pass through unchanged so a repair tool
        can replay them onto the source topic."""
        dlq = self._bus.topic(self.dead_letter_topic)
        for record in batch:
            dlq.publish(record.key, record.value)
        self.dead_lettered += len(batch)

    def _run(self) -> None:
        consumer = self._bus.consumer(self._topic_name, self._group_id)
        consumer.seek_to_committed()
        while not self._stop.is_set():
            # During a retry cycle, poll EXACTLY the extent of the batch
            # that first failed (per-partition end offsets): records
            # arriving during the backoff must not join the retried batch,
            # or parking would dead-letter (and commit past) innocent
            # records that were never at fault.
            until = self._failing[2] if self._failing else None
            batch = consumer.poll(self._max_records,
                                  timeout_s=self._poll_timeout_s,
                                  until=until)
            if not batch:
                if self._failing:
                    # the failing extent yielded nothing (e.g. retention
                    # truncated it): abandon the retry cycle rather than
                    # re-polling an empty extent forever
                    self._failing = None
                    consumer.seek_to_committed()
                continue
            try:
                self._handler(batch)
                self._bus.commit(consumer)
                self._failing = None
            except Exception:
                self.errors += 1
                fingerprint = tuple(consumer.committed)
                if self._failing and self._failing[0] == fingerprint:
                    retries = self._failing[1] + 1
                    extent = self._failing[2]
                else:
                    retries = 1
                    extent = batch_extent(batch)
                self._failing = (fingerprint, retries, extent)
                if retries > self._max_retries:
                    self._park(batch)
                    self._bus.commit(consumer)  # advance past the poison
                    self._failing = None
                else:
                    consumer.seek_to_committed()
                    backoff = min(0.05 * (2 ** (retries - 1)),
                                  self._max_backoff_s)
                    self._stop.wait(jittered(backoff))

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=timeout_s)
            self._thread = None
