"""Networked bus edge: TCP producer/consumer endpoint for the event bus.

Reference: Kafka is a *network* broker — any process can produce to or
consume from a topic (MicroserviceKafkaConsumer.java:115-121 polls over the
wire). The in-proc `runtime.bus.EventBus` replaces the broker for the
single-host fast path; this module is the pod-edge complement: a TPU-host
process runs `BusServer` over its bus, and edge processes (gateway boxes,
protocol bridges, non-TPU ingest tiers) use `BusClient` /
`RemoteConsumerHost` to publish and consume over TCP with the same
at-least-once committed-offset semantics.

Protocol: length-prefixed msgpack frames, one request -> one response per
frame, pipelined per connection. Batched publishes amortize round-trips
(the DeviceEventBuffer trade); polls long-poll server-side so edge
consumers don't spin.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Callable, List, Optional, Tuple

import msgpack

from sitewhere_tpu.runtime.bus import (EventBus, Record, batch_extent,
                                       jittered)
from sitewhere_tpu.runtime.faults import fault_point
from sitewhere_tpu.runtime.recovery import EpochFence, StaleEpochError
from sitewhere_tpu.runtime.tracing import GLOBAL_TRACER, extract_traceparent

_LEN = struct.Struct("<I")
_MAX_FRAME = 64 * 1024 * 1024


class BusNetError(Exception):
    """Protocol or transport failure on the networked bus edge."""


class StaleEpochBusError(BusNetError, StaleEpochError):
    """Fencing rejection over the wire: a request stamped with an epoch
    below the server's fenced floor for its resource. Catchable as a
    BusNetError (publishers park, consumers back off — the zombie's
    rows never reach live state) AND as the structured StaleEpochError
    (resource/epoch/floor ride the exception)."""


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise BusNetError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _send_frame(sock: socket.socket, obj) -> None:
    payload = msgpack.packb(obj, use_bin_type=True)
    if len(payload) > _MAX_FRAME:
        raise BusNetError(f"frame {len(payload)} exceeds {_MAX_FRAME}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket):
    (length,) = _LEN.unpack(_read_exact(sock, _LEN.size))
    if length > _MAX_FRAME:
        raise BusNetError(f"frame {length} exceeds {_MAX_FRAME}")
    return msgpack.unpackb(_read_exact(sock, length), raw=False)


class _GroupCoordinator:
    """Networked consumer-group membership: each connected consumer of a
    (topic, group) is a member and owns a disjoint partition subset
    (index i of n members owns partitions p with p % n == i — the Kafka
    range/round-robin assignment role). Members poll and commit ONLY their
    partitions, so one member's commit can never advance offsets past
    another member's in-flight batch; on member loss its partitions re-seek
    to committed and reassign to the survivors (rebalance + replay)."""

    def __init__(self, bus: EventBus):
        self.bus = bus
        self._members: dict = {}   # (topic, group) -> list of member ids
        self._lock = threading.Lock()

    def _ensure(self, topic: str, group: str, member: int) -> bool:
        """Register membership; True when this call changed the group."""
        with self._lock:
            members = self._members.setdefault((topic, group), [])
            if member not in members:
                members.append(member)
                return True
            return False

    def owned(self, topic: str, group: str, member: int) -> List[int]:
        if self._ensure(topic, group, member):
            # Rebalance: partitions just moved between members, and a
            # previous owner's uncommitted position advances must not leak
            # to the new owner — everyone replays from committed
            # (at-least-once; duplicates possible, loss not).
            self.bus.consumer(topic, group).seek_to_committed()
        n_parts = len(self.bus.topic(topic).partitions)
        with self._lock:
            members = self._members[(topic, group)]
            index = members.index(member)
            count = len(members)
        return [p for p in range(n_parts) if p % count == index]

    def leave_all(self, member: int) -> None:
        with self._lock:
            affected = [(key, members) for key, members in
                        self._members.items() if member in members]
            for _, members in affected:
                members.remove(member)
        for (topic, group), _ in affected:
            # released partitions replay from committed on the next owner
            self.bus.consumer(topic, group).seek_to_committed()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        bus: EventBus = self.server.bus  # type: ignore[attr-defined]
        coordinator = self.server.coordinator  # type: ignore[attr-defined]
        member = id(self)
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                try:
                    req = _recv_frame(sock)
                except (BusNetError, OSError):
                    return  # client went away (or stop() severed us)
                # drill directives (runtime/faults.py; no-ops disarmed):
                # a partition window severs every connection on arrival,
                # a drop eats the RESPONSE after the op ran (the
                # lost-reply case BusClient._rpc's pre_retry exists for),
                # a delay stalls the reply in flight.
                if fault_point("busnet_partition") is not None:
                    return
                try:
                    # W3C trace propagation: a client-stamped envelope
                    # opens a server span parented on the caller's
                    # context, stitching feeder -> mesh-host journeys.
                    # Unstamped requests (the overwhelming steady state)
                    # pay one dict lookup.
                    ctx = extract_traceparent(req.get("traceparent"))
                    if ctx is not None:
                        with GLOBAL_TRACER.span(
                                f"busnet.{req.get('op')}", parent=ctx,
                                topic=str(req.get("topic", ""))):
                            resp = self._dispatch(
                                bus, coordinator, member, req,
                                self.server.fence,  # type: ignore[attr-defined]
                                getattr(self.server,
                                        "telemetry_provider", None),
                                getattr(self.server, "op_handlers", None))
                    else:
                        resp = self._dispatch(
                            bus, coordinator, member, req,
                            self.server.fence,  # type: ignore[attr-defined]
                            getattr(self.server, "telemetry_provider",
                                    None),
                            getattr(self.server, "op_handlers", None))
                    fault_point("busnet_delay")
                    if fault_point("busnet_drop") is not None:
                        return
                    _send_frame(sock, resp)
                except (BusNetError, OSError):
                    return
                except Exception as exc:  # report, keep the connection
                    try:
                        _send_frame(sock, {"ok": False, "error": str(exc)})
                    except (BusNetError, OSError):
                        return
        finally:
            untrack = getattr(self.server, "untrack_connection", None)
            if untrack is not None:
                untrack(sock)
            coordinator.leave_all(member)

    @staticmethod
    def _dispatch(bus: EventBus, coordinator: _GroupCoordinator,
                  member: int, req, fence: EpochFence,
                  telemetry_provider: Optional[Callable[[], dict]] = None,
                  op_handlers: Optional[dict] = None) -> dict:
        op = req.get("op")

        def _parts(topic: str, group: str):
            # Explicit partition pinning: a leased owner (feeders/) names
            # the partitions its lease covers instead of taking the
            # connection-scoped group assignment — ownership then follows
            # the LEASE (durable, fenced, stealable at epoch+1), not the
            # TCP connection. Absent, the coordinator assignment applies.
            pinned = req.get("partitions")
            if pinned is not None:
                return [int(p) for p in pinned]
            return coordinator.owned(topic, group, member)

        # Epoch fencing (runtime/recovery.py): a request stamped with a
        # fencing identity is admitted only at-or-above the resource's
        # fenced floor. Floors auto-learn from admitted traffic (a
        # restarted writer's newer epoch fences its old incarnation) and
        # are raised explicitly by the takeover broadcast below — the
        # zombie/split-brain write guard. Unstamped requests pass
        # (backward compatible; fencing is opt-in per writer). Two stamp
        # forms check the same floors: the single `fence` identity, and
        # the multi-key `fences` list ([key, epoch] pairs) consume-side
        # ops use to cover every leased partition in one request — a
        # fenced-out zombie's poll/commit/seek must bounce BEFORE it can
        # move the shared server-side cursor (records a zombie silently
        # skips past would otherwise look like replays downstream and be
        # dropped — permanent loss, not duplicates).
        fence_key = req.get("fence")

        def _stale_reply():
            checks = []
            if fence_key is not None:
                checks.append((str(fence_key), int(req.get("epoch", 0))))
            for pair in req.get("fences") or []:
                checks.append((str(pair[0]), int(pair[1])))
            for key, epoch in checks:
                if not fence.admit(key, epoch):
                    floor = fence.floor(key)
                    return {"ok": False, "stale_epoch": True,
                            "fence": key, "epoch": epoch, "floor": floor,
                            "error": f"stale epoch {epoch} < fenced "
                                     f"floor {floor} for '{key}'"}
            return None

        if op != "fence":
            stale = _stale_reply()
            if stale is not None:
                return stale
        if op == "fence":
            # takeover broadcast: raise the floor for a (usually dead)
            # writer's identity so its surviving incarnation is rejected
            floor = fence.fence(str(req["key"]), int(req["epoch"]))
            return {"ok": True, "floor": floor}
        if op == "publish":
            topic = bus.topic(req["topic"])
            records = req["records"]
            if not records:
                return {"ok": True, "count": 0, "last": None}
            last = topic.publish_many(records)
            return {"ok": True, "count": len(records), "last": list(last)}
        if op == "poll":
            topic, group = req["topic"], req["group"]
            owned = _parts(topic, group)
            consumer = bus.consumer(topic, group)
            commit_at = req.get("commit_at")
            if commit_at:
                # piggybacked EXPLICIT-offset commit of the previous batch:
                # edge consumers save a full round trip per batch
                # (poll+commit -> one request). Explicit offsets (not
                # commit-position) so a later failed batch can never be
                # committed by accident.
                bus.commit_at(consumer,
                              {int(k): int(v) for k, v in commit_at.items()},
                              partitions=owned)
            until = req.get("until")
            if until is not None:
                until = {int(k): int(v) for k, v in until.items()}
            batch = consumer.poll(req.get("max", 4096),
                                  timeout_s=min(float(req.get("timeout_s",
                                                              0.0)), 30.0),
                                  partitions=owned, until=until)
            if fence_key is not None or req.get("fences"):
                # re-validate AFTER the poll: a takeover that raised the
                # floor while this poll was in flight must not let the
                # zombie's cursor advance stand — rewind to committed
                # (idempotent with the successor's own seek) and reject,
                # so no record is silently skipped past
                stale = _stale_reply()
                if stale is not None:
                    consumer.seek_to_committed(partitions=owned)
                    return stale
            return {"ok": True, "records": [
                [r.partition, r.offset, r.key, r.value, r.timestamp_ms]
                for r in batch]}
        if op == "commit":
            topic, group = req["topic"], req["group"]
            owned = _parts(topic, group)
            bus.commit(bus.consumer(topic, group), partitions=owned)
            return {"ok": True}
        if op == "commit_at":
            topic, group = req["topic"], req["group"]
            owned = _parts(topic, group)
            bus.commit_at(bus.consumer(topic, group),
                          {int(k): int(v)
                           for k, v in req.get("offsets", {}).items()},
                          partitions=owned)
            return {"ok": True}
        if op == "seek_committed":
            topic, group = req["topic"], req["group"]
            owned = _parts(topic, group)
            bus.consumer(topic, group).seek_to_committed(partitions=owned)
            return {"ok": True}
        if op == "end_offsets":
            return {"ok": True,
                    "offsets": bus.topic(req["topic"]).end_offsets()}
        if op == "topics":
            return {"ok": True, "topics": bus.topics()}
        if op == "ping":
            return {"ok": True, "ts": int(time.time() * 1000)}
        if op == "telemetry":
            # cluster fan-in: hand back this process's observability
            # snapshot (metrics/flight/age/prometheus text) assembled by
            # whatever the host wired in via BusServer.telemetry_provider
            if telemetry_provider is None:
                return {"ok": False, "error": "no telemetry provider"}
            return {"ok": True, "telemetry": telemetry_provider()}
        if op_handlers:
            handler = op_handlers.get(op)
            if handler is not None:
                # pluggable subsystem ops (BusServer.register_op): the
                # handler sees the raw request AFTER the fence admit above
                # and returns the response dict (ok/error convention)
                return handler(req)
        return {"ok": False, "error": f"unknown op {op!r}"}


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._connections: set = set()
        self._connections_lock = threading.Lock()

    def process_request(self, request, client_address):
        # Track in the accept loop, not the handler thread: registration
        # must happen-before shutdown() returns, or a connection accepted
        # during stop() would escape sever_connections().
        self.track_connection(request)
        super().process_request(request, client_address)

    def track_connection(self, sock) -> None:
        with self._connections_lock:
            self._connections.add(sock)

    def untrack_connection(self, sock) -> None:
        with self._connections_lock:
            self._connections.discard(sock)

    def sever_connections(self) -> None:
        """Force-close live client connections. Without this, a stopped
        server's handler threads keep serving clients against the OLD bus
        instance — publishes 'succeed' into dead state and are lost when
        a replacement server takes the port."""
        with self._connections_lock:
            conns = list(self._connections)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class BusServer:
    """Expose an EventBus on TCP (the broker's network face)."""

    def __init__(self, bus: EventBus, host: str = "127.0.0.1",
                 port: int = 0):
        self.bus = bus
        self._server = _Server((host, port), _Handler)
        self._server.bus = bus  # type: ignore[attr-defined]
        self._server.coordinator = _GroupCoordinator(bus)  # type: ignore[attr-defined]
        self._server.fence = EpochFence()  # type: ignore[attr-defined]
        self._server.telemetry_provider = None  # type: ignore[attr-defined]
        self._server.op_handlers = {}  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    def register_op(self, name: str,
                    handler: Callable[[dict], dict]) -> None:
        """Mount a subsystem op on this server's dispatch table (e.g. the
        feeder fleet's `feeder_*` family, feeders/service.py). The
        handler receives the raw request dict after epoch-fence admission
        and returns the response dict; exceptions become `{"ok": False,
        "error": ...}` replies on a healthy connection. Built-in ops
        cannot be shadowed — dispatch consults the registry last."""
        self._server.op_handlers[str(name)] = handler  # type: ignore[attr-defined]

    @property
    def fence(self) -> EpochFence:
        """The server's per-resource epoch floors (fencing state)."""
        return self._server.fence  # type: ignore[attr-defined]

    @property
    def telemetry_provider(self) -> Optional[Callable[[], dict]]:
        """Zero-arg callable answering the `telemetry` op (cluster
        fan-in); None rejects the op."""
        return self._server.telemetry_provider  # type: ignore[attr-defined]

    @telemetry_provider.setter
    def telemetry_provider(self, fn: Optional[Callable[[], dict]]) -> None:
        self._server.telemetry_provider = fn  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="bus-server", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._server.sever_connections()
        self._thread.join(timeout=5.0)
        self._thread = None


class BusClient:
    """Edge-process handle onto a remote bus. Thread-safe (one in-flight
    request per connection); reconnects on transport failure — safe because
    every operation is idempotent-or-at-least-once (a retried publish can
    duplicate, exactly the at-least-once contract)."""

    def __init__(self, host: str, port: int, timeout_s: float = 35.0,
                 retries: int = 2):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.retries = retries
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # fencing identity: once set, every request is stamped with
        # (fence, epoch) and the server rejects it below the floor
        self._fence_key: Optional[str] = None
        self._epoch = 0

    def set_epoch(self, fence_key: str, epoch: int) -> None:
        """Adopt a fencing identity: stamp subsequent requests with this
        resource key + epoch (minted by runtime/recovery.py at boot or
        takeover)."""
        self._fence_key = str(fence_key)
        self._epoch = int(epoch)

    def fence(self, key: str, epoch: int) -> int:
        """Raise the server's floor for `key` to at least `epoch` (the
        takeover broadcast); returns the resulting floor."""
        return int(self._rpc({"op": "fence", "key": str(key),
                              "epoch": int(epoch)})["floor"])

    def _connect(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def _rpc(self, req: dict, pre_retry: Optional[dict] = None) -> dict:
        """One request/response. On transport failure, reconnect and retry;
        `pre_retry` is sent first after a reconnect — poll uses it to re-seek
        the server-side cursor to committed, because a poll whose RESPONSE
        was lost already advanced the position (retrying blindly would skip
        those records and the next commit would lose them permanently)."""
        if self._fence_key is not None and req.get("op") != "fence" \
                and "fence" not in req:
            req = dict(req, fence=self._fence_key, epoch=self._epoch)
        if "traceparent" not in req:
            # trace propagation mirrors the fence stamp: when the calling
            # thread has an active span (sampled journeys, REST ingress),
            # its W3C context rides the envelope so the server span
            # stitches into the same trace. No span -> one dict lookup.
            tp = GLOBAL_TRACER.current_traceparent()
            if tp is not None:
                req = dict(req, traceparent=tp)
        with self._lock:
            last: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                try:
                    sock = self._connect()
                    if pre_retry is not None and attempt > 0:
                        _send_frame(sock, pre_retry)
                        ack = _recv_frame(sock)
                        if not ack.get("ok"):
                            raise BusNetError(
                                ack.get("error", "pre-retry failed"))
                    _send_frame(sock, req)
                    resp = _recv_frame(sock)
                    if not resp.get("ok"):
                        if resp.get("stale_epoch"):
                            # fenced: structured, non-retryable — the
                            # socket stays healthy, the WRITER is dead
                            raise StaleEpochBusError(
                                str(resp.get("fence", "")),
                                int(resp.get("epoch", 0)),
                                int(resp.get("floor", 0)))
                        raise BusNetError(resp.get("error", "request failed"))
                    return resp
                except (OSError, BusNetError) as exc:
                    if isinstance(exc, BusNetError) and self._sock is not None:
                        # protocol-level error on a healthy connection:
                        # don't burn the socket or retry a rejected request
                        if str(exc) != "connection closed":
                            raise
                    last = exc
                    self.close()
                    if attempt < self.retries:
                        # capped exponential backoff with equal jitter:
                        # immediate lockstep reconnects from every client
                        # hammer exactly the server trying to come back
                        time.sleep(jittered(min(0.05 * (2 ** attempt),
                                                1.0)))
            raise BusNetError(f"bus rpc failed after retries: {last}")

    def publish(self, topic: str, key: bytes, value: bytes
                ) -> Tuple[int, int]:
        resp = self._rpc({"op": "publish", "topic": topic,
                          "records": [[key, value]]})
        part, offset = resp["last"]
        return part, offset

    def publish_batch(self, topic: str,
                      records: List[Tuple[bytes, bytes]]) -> int:
        if not records:
            return 0
        return self._rpc({"op": "publish", "topic": topic,
                          "records": [[k, v] for k, v in records]})["count"]

    def poll(self, topic: str, group: str, max_records: int = 4096,
             timeout_s: float = 0.0,
             until: Optional[dict] = None,
             commit_at: Optional[dict] = None,
             partitions: Optional[List[int]] = None,
             fences: Optional[List] = None) -> List[Record]:
        req = {"op": "poll", "topic": topic, "group": group,
               "max": max_records, "timeout_s": timeout_s}
        if commit_at:
            req["commit_at"] = {str(k): int(v) for k, v in commit_at.items()}
        if until is not None:
            req["until"] = {str(k): int(v) for k, v in until.items()}
        pre_retry = {"op": "seek_committed", "topic": topic, "group": group}
        if partitions is not None:
            # lease-pinned consumption (feeders/): poll exactly the named
            # partitions regardless of the coordinator's connection-scoped
            # assignment; the re-seek after a lost reply pins the same set
            req["partitions"] = [int(p) for p in partitions]
            pre_retry["partitions"] = [int(p) for p in partitions]
        if fences:
            # per-partition epoch stamps: a fenced-out caller bounces
            # with stale_epoch instead of advancing the shared cursor;
            # the lost-reply re-seek carries the same stamps so a
            # zombie's retry cannot rewind a successor's partition
            stamps = [[str(k), int(e)] for k, e in fences]
            req["fences"] = stamps
            pre_retry["fences"] = stamps
        resp = self._rpc(req, pre_retry=pre_retry)
        return [Record(topic, part, offset, key, value, ts)
                for part, offset, key, value, ts in resp["records"]]

    def commit(self, topic: str, group: str) -> None:
        self._rpc({"op": "commit", "topic": topic, "group": group})

    def commit_at(self, topic: str, group: str, offsets: dict,
                  partitions: Optional[List[int]] = None,
                  fences: Optional[List] = None) -> None:
        """Commit explicit per-partition exclusive end offsets."""
        req = {"op": "commit_at", "topic": topic, "group": group,
               "offsets": {str(k): int(v) for k, v in offsets.items()}}
        if partitions is not None:
            req["partitions"] = [int(p) for p in partitions]
        if fences:
            req["fences"] = [[str(k), int(e)] for k, e in fences]
        self._rpc(req)

    def seek_committed(self, topic: str, group: str,
                       partitions: Optional[List[int]] = None,
                       fences: Optional[List] = None) -> None:
        req = {"op": "seek_committed", "topic": topic, "group": group}
        if partitions is not None:
            # pinned seek (feeders/): rewind ONLY the named partitions —
            # a lease takeover must re-read its predecessor's uncommitted
            # tail without disturbing other live feeders' cursors
            req["partitions"] = [int(p) for p in partitions]
        if fences:
            req["fences"] = [[str(k), int(e)] for k, e in fences]
        self._rpc(req)

    def end_offsets(self, topic: str) -> List[int]:
        return self._rpc({"op": "end_offsets", "topic": topic})["offsets"]

    def topics(self) -> List[str]:
        return self._rpc({"op": "topics"})["topics"]

    def call(self, op: str, **fields) -> dict:
        """Invoke a registered subsystem op (BusServer.register_op) —
        same fencing stamp, tracing envelope, and reconnect/backoff
        policy as the built-in ops. Returns the full response dict."""
        return self._rpc(dict(fields, op=str(op)))

    def telemetry(self) -> dict:
        """Fetch the remote process's observability snapshot (cluster
        telemetry fan-in)."""
        return self._rpc({"op": "telemetry"})["telemetry"]

    def ping(self) -> bool:
        try:
            return bool(self._rpc({"op": "ping"})["ok"])
        except BusNetError:
            return False

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class RemoteConsumerHost:
    """ConsumerHost twin for edge processes: poll/commit over a BusClient.
    Handler exceptions leave offsets uncommitted server-side; the host
    re-seeks to committed so the batch redelivers (at-least-once) — with
    the same exponential-backoff retry budget and dead-letter parking as
    the in-proc ConsumerHost, so a poison batch can't spin an edge
    consumer forever."""

    def __init__(self, client: BusClient, topic_name: str, group_id: str,
                 handler: Callable[[List[Record]], None],
                 max_records: int = 4096, poll_timeout_s: float = 0.5,
                 max_retries: int = 12, max_backoff_s: float = 30.0,
                 dead_letter_topic: Optional[str] = None):
        self._client = client
        self._topic_name = topic_name
        self._group_id = group_id
        self._handler = handler
        self._max_records = max_records
        self._poll_timeout_s = poll_timeout_s
        self._max_retries = max_retries
        self._max_backoff_s = max_backoff_s
        self.dead_letter_topic = (dead_letter_topic
                                  or f"{topic_name}.dead-letter")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.errors = 0
        self.dead_lettered = 0
        # ((partition, offset) of the failing batch head, retries,
        # per-partition exclusive end offsets of the first failing batch)
        self._failing: Optional[tuple] = None
        # a successfully-handled batch's commit (its EXPLICIT per-partition
        # extent) piggybacks on the NEXT poll request — one round trip per
        # batch instead of two; flushed explicitly on stop and before
        # failure-path seeks
        self._pending_extent: Optional[dict] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"remote-consumer-{self._group_id}",
            daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            self._client.seek_committed(self._topic_name, self._group_id)
        except BusNetError:
            pass  # server unreachable at boot: first poll retries anyway
        while not self._stop.is_set():
            try:
                # retry cycles re-poll exactly the original failing batch's
                # per-partition extent (see ConsumerHost._run — records
                # arriving during backoff must not be parked with the
                # poison)
                until = self._failing[2] if self._failing else None
                batch = self._client.poll(self._topic_name, self._group_id,
                                          self._max_records,
                                          timeout_s=self._poll_timeout_s,
                                          until=until,
                                          commit_at=self._pending_extent)
                self._pending_extent = None
            except BusNetError:
                self.errors += 1
                # a failed poll may have advanced the server-side cursor
                # (lost response): rewind to committed before polling again
                try:
                    self._client.seek_committed(self._topic_name,
                                                self._group_id)
                except BusNetError:
                    pass
                time.sleep(jittered(0.3))  # desync reconnecting consumers
                continue
            if not batch:
                if self._failing:
                    # empty extent poll (partition reassigned by a
                    # rebalance, lost seek, retention): abandon the retry
                    # cycle instead of hot-spinning RPCs on it forever
                    self._failing = None
                    try:
                        self._client.seek_committed(self._topic_name,
                                                    self._group_id)
                    except BusNetError:
                        pass
                    self._stop.wait(0.2)
                continue
            try:
                self._handler(batch)
                self._pending_extent = batch_extent(batch)  # next poll commits
                self._failing = None
            except Exception:
                self.errors += 1
                # the PREVIOUS batch's deferred commit must land before any
                # seek_to_committed below, or its records would rejoin (and
                # eventually be dead-lettered with) the failing batch
                self._flush_pending_commit()
                fingerprint = (batch[0].partition, batch[0].offset)
                if self._failing and self._failing[0] == fingerprint:
                    retries = self._failing[1] + 1
                    extent = self._failing[2]
                else:
                    retries = 1
                    extent = batch_extent(batch)
                self._failing = (fingerprint, retries, extent)
                try:
                    if retries > self._max_retries:
                        self._client.publish_batch(
                            self.dead_letter_topic,
                            [(r.key, r.value) for r in batch])
                        self.dead_lettered += len(batch)
                        self._client.commit(self._topic_name, self._group_id)
                        self._failing = None
                    else:
                        self._client.seek_committed(self._topic_name,
                                                    self._group_id)
                        self._stop.wait(jittered(
                            min(0.05 * (2 ** (retries - 1)),
                                self._max_backoff_s)))
                except BusNetError:
                    pass

    def _flush_pending_commit(self, bounded: bool = False) -> None:
        if self._pending_extent is None:
            return
        old = (self._client.timeout_s, self._client.retries)
        if bounded:
            # shutdown must stay bounded: one short attempt, not the
            # client's full reconnect/retry budget (~minutes against a
            # hung server). An unflushed commit only costs redelivery.
            self._client.timeout_s, self._client.retries = 2.0, 0
        try:
            self._client.commit_at(self._topic_name, self._group_id,
                                   self._pending_extent)
            self._pending_extent = None
        except BusNetError:
            pass  # stays pending; redelivery is legal (at-least-once)
        finally:
            if bounded:
                self._client.timeout_s, self._client.retries = old

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        # flush the last handled batch's deferred commit (otherwise a
        # clean shutdown would redeliver it on the next start — legal
        # under at-least-once, but wasteful)
        self._flush_pending_commit(bounded=True)
