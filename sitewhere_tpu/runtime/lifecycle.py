"""Hierarchical component lifecycle state machine.

Reference: sitewhere-core-lifecycle LifecycleComponent.java:40 — components move
Initializing -> Starting -> Started -> Stopping -> Stopped (plus error/paused
states), own nested child components that are initialized/started with them and
stopped in reverse, and report progress through a monitor. CompositeLifecycleStep
mirrors CompositeLifecycleStep.java; TenantEngineLifecycleComponent's tenant
scoping is the `tenant_id` attribute here.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.errors import LifecycleError

LOGGER = logging.getLogger("sitewhere.lifecycle")


class LifecycleStatus(enum.Enum):
    INITIALIZING = "Initializing"
    INITIALIZATION_ERROR = "InitializationError"
    STOPPED = "Stopped"
    STOPPED_WITH_ERRORS = "StoppedWithErrors"
    STARTING = "Starting"
    STARTED = "Started"
    STARTED_WITH_ERRORS = "StartedWithErrors"
    PAUSING = "Pausing"
    PAUSED = "Paused"
    STOPPING = "Stopping"
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"
    LIFECYCLE_ERROR = "LifecycleError"


# Statuses from which start() is legal (reference LifecycleComponent.lifecycleStart:242)
_STARTABLE = {
    LifecycleStatus.STOPPED,
    LifecycleStatus.STOPPED_WITH_ERRORS,
    LifecycleStatus.PAUSED,
}


class LifecycleProgressMonitor:
    """Collects progress messages during lifecycle transitions
    (reference: LifecycleProgressMonitor.java)."""

    def __init__(self, task_name: str = ""):
        self.task_name = task_name
        self.messages: List[str] = []

    def report(self, message: str) -> None:
        self.messages.append(message)
        LOGGER.debug("[%s] %s", self.task_name, message)


class LifecycleComponent:
    """Base class for every managed component in the framework.

    Subclasses override `on_initialize` / `on_start` / `on_stop` /
    `on_terminate`. Nested components registered with `add_nested` are
    initialized+started after the parent's hook and stopped in reverse order
    before the parent's stop hook, matching the reference's
    initializeNestedComponent/startNestedComponent flow
    (LifecycleComponent.java:218+).
    """

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__
        self.status = LifecycleStatus.STOPPED
        self.error: Optional[BaseException] = None
        self.tenant_id: Optional[str] = None  # set for tenant-engine-scoped components
        self.created_at = time.time()
        self._nested: List[LifecycleComponent] = []
        self._lock = threading.RLock()
        self._initialized = False

    # -- composition ---------------------------------------------------------

    def add_nested(self, component: "LifecycleComponent") -> "LifecycleComponent":
        with self._lock:
            self._nested.append(component)
            if component.tenant_id is None:
                component.tenant_id = self.tenant_id
        return component

    @property
    def nested(self) -> List["LifecycleComponent"]:
        return list(self._nested)

    def find(self, name: str) -> Optional["LifecycleComponent"]:
        """Depth-first lookup by component name."""
        if self.name == name:
            return self
        for child in self._nested:
            found = child.find(name)
            if found is not None:
                return found
        return None

    # -- hooks (override) ----------------------------------------------------

    def on_initialize(self, monitor: LifecycleProgressMonitor) -> None:
        pass

    def on_start(self, monitor: LifecycleProgressMonitor) -> None:
        pass

    def on_stop(self, monitor: LifecycleProgressMonitor) -> None:
        pass

    def on_terminate(self, monitor: LifecycleProgressMonitor) -> None:
        pass

    # -- transitions ---------------------------------------------------------

    def initialize(self, monitor: Optional[LifecycleProgressMonitor] = None) -> None:
        monitor = monitor or LifecycleProgressMonitor(f"Initialize {self.name}")
        with self._lock:
            self.status = LifecycleStatus.INITIALIZING
            try:
                monitor.report(f"Initializing {self.name}")
                self.on_initialize(monitor)
                for child in self._nested:
                    child.initialize(monitor)
                self._initialized = True
                self.status = LifecycleStatus.STOPPED
            except BaseException as exc:
                self.error = exc
                self.status = LifecycleStatus.INITIALIZATION_ERROR
                raise LifecycleError(f"{self.name} failed to initialize: {exc}") from exc

    def start(self, monitor: Optional[LifecycleProgressMonitor] = None) -> None:
        monitor = monitor or LifecycleProgressMonitor(f"Start {self.name}")
        with self._lock:
            if self.status == LifecycleStatus.STARTED:
                return
            if not self._initialized:
                self.initialize(monitor)
            if self.status not in _STARTABLE:
                raise LifecycleError(
                    f"Cannot start {self.name} from status {self.status.value}")
            self.status = LifecycleStatus.STARTING
            try:
                monitor.report(f"Starting {self.name}")
                self.on_start(monitor)
                errors = []
                for child in self._nested:
                    try:
                        child.start(monitor)
                    except BaseException as exc:  # reference: StartedWithErrors
                        errors.append(exc)
                        LOGGER.exception("Nested component %s failed to start", child.name)
                self.status = (LifecycleStatus.STARTED_WITH_ERRORS if errors
                               else LifecycleStatus.STARTED)
            except BaseException as exc:
                self.error = exc
                self.status = LifecycleStatus.LIFECYCLE_ERROR
                raise LifecycleError(f"{self.name} failed to start: {exc}") from exc

    def stop(self, monitor: Optional[LifecycleProgressMonitor] = None) -> None:
        monitor = monitor or LifecycleProgressMonitor(f"Stop {self.name}")
        with self._lock:
            if self.status in (LifecycleStatus.STOPPED, LifecycleStatus.TERMINATED):
                return
            self.status = LifecycleStatus.STOPPING
            errors = []
            for child in reversed(self._nested):
                try:
                    child.stop(monitor)
                except BaseException as exc:
                    errors.append(exc)
                    LOGGER.exception("Nested component %s failed to stop", child.name)
            try:
                monitor.report(f"Stopping {self.name}")
                self.on_stop(monitor)
            except BaseException as exc:
                errors.append(exc)
                LOGGER.exception("Component %s failed to stop", self.name)
            self.status = (LifecycleStatus.STOPPED_WITH_ERRORS if errors
                           else LifecycleStatus.STOPPED)

    def terminate(self, monitor: Optional[LifecycleProgressMonitor] = None) -> None:
        monitor = monitor or LifecycleProgressMonitor(f"Terminate {self.name}")
        with self._lock:
            if self.status not in (LifecycleStatus.STOPPED,
                                   LifecycleStatus.STOPPED_WITH_ERRORS):
                self.stop(monitor)
            self.status = LifecycleStatus.TERMINATING
            for child in reversed(self._nested):
                child.terminate(monitor)
            self.on_terminate(monitor)
            self.status = LifecycleStatus.TERMINATED

    def restart(self) -> None:
        """Stop + start (reference: tenant-engine restart,
        MultitenantMicroservice.java:284)."""
        self.stop()
        self.start()

    # -- introspection -------------------------------------------------------

    def is_running(self) -> bool:
        return self.status in (LifecycleStatus.STARTED,
                               LifecycleStatus.STARTED_WITH_ERRORS)

    def state_tree(self) -> Dict:
        """Serializable status snapshot of this subtree (feeds the topology
        broadcast, reference: IMicroserviceState)."""
        return {
            "name": self.name,
            "status": self.status.value,
            "tenantId": self.tenant_id,
            "error": str(self.error) if self.error else None,
            "nested": [c.state_tree() for c in self._nested],
        }


class CompositeLifecycleStep:
    """Ordered list of named lifecycle actions run under one monitor
    (reference: CompositeLifecycleStep.java)."""

    def __init__(self, name: str):
        self.name = name
        self._steps: List[tuple] = []

    def add(self, description: str, action: Callable[[], None]) -> None:
        self._steps.append((description, action))

    def add_initialize(self, component: LifecycleComponent) -> None:
        self.add(f"Initialize {component.name}", component.initialize)

    def add_start(self, component: LifecycleComponent) -> None:
        self.add(f"Start {component.name}", component.start)

    def add_stop(self, component: LifecycleComponent) -> None:
        self.add(f"Stop {component.name}", component.stop)

    def execute(self, monitor: Optional[LifecycleProgressMonitor] = None) -> None:
        monitor = monitor or LifecycleProgressMonitor(self.name)
        for description, action in self._steps:
            monitor.report(description)
            action()
