"""Per-engine health state machine: healthy → degraded → draining → failed.

The degradation ladder the fault drills exercise:

  healthy    steady state; every submit lands first try
  degraded   transient failures being absorbed — the engine is retrying
             (H2D / dispatch) or the admission controller is shedding
  draining   poison work is being moved aside: a batch exhausted its
             retry budget and parked on a dead-letter topic; the engine
             keeps stepping but an operator owes it a replay
  failed     a step failure survived every retry AND could not be parked
             (or state was lost mid-donation) — sticky until reset()

Recovery: `recover_after` consecutive clean submits walk degraded or
draining back to healthy. `failed` never self-clears — the supervisor
(gang restart) or an operator reset is the only way back, mirroring the
reference's tenant-engine failed state.

Surfaced on `GET /api/instance/topology` (``pipeline_health``), as the
``pipeline.health_state`` gauge on `GET /metrics` (0=healthy 1=degraded
2=draining 3=failed), and counted per transition on the engine-scoped
``health_transitions`` counter.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

LOGGER = logging.getLogger("sitewhere.health")

HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"
FAILED = "failed"

STATE_ORDER = (HEALTHY, DEGRADED, DRAINING, FAILED)
STATE_CODES = {name: i for i, name in enumerate(STATE_ORDER)}


class EngineHealth:
    """Tiny lock-guarded state machine; note_* calls are O(1) and only
    appear on failure paths (note_success is a counter bump + one branch,
    cheap enough for every submit)."""

    def __init__(self, name: str, metrics=None, recover_after: int = 8,
                 ring_size: int = 32):
        self.name = name
        self.recover_after = int(recover_after)
        self.state = HEALTHY
        self.transitions = 0
        self.last_transition_ms: Optional[int] = None
        self.last_cause: Optional[str] = None
        # recent transitions (state, cause, timestamp) for post-incident
        # triage — counters say HOW MANY, the ring says WHAT happened
        self._ring: "deque[Dict]" = deque(maxlen=int(ring_size))
        self._streak = 0  # consecutive clean submits while impaired
        self._lock = threading.Lock()
        self._transition_counter = (
            metrics.counter("health_transitions") if metrics is not None
            else None)

    @property
    def code(self) -> int:
        return STATE_CODES[self.state]

    def _move(self, state: str, cause: str) -> None:
        # caller holds the lock
        if self.state == state:
            return
        LOGGER.info("engine '%s' health %s -> %s (%s)",
                    self.name, self.state, state, cause)
        self.state = state
        self.transitions += 1
        self.last_transition_ms = int(time.time() * 1000)
        self.last_cause = cause
        self._ring.append({"state": state, "cause": cause,
                           "at_ms": self.last_transition_ms})
        self._streak = 0
        if self._transition_counter is not None:
            self._transition_counter.inc()

    # -- events --------------------------------------------------------
    def note_success(self) -> None:
        if self.state == HEALTHY:
            return
        with self._lock:
            if self.state in (DEGRADED, DRAINING):
                self._streak += 1
                if self._streak >= self.recover_after:
                    self._move(HEALTHY, "recovered")

    def note_retry(self, cause: str = "transient step failure") -> None:
        with self._lock:
            if self.state == HEALTHY:
                self._move(DEGRADED, cause)
            else:
                self._streak = 0

    def note_shed(self) -> None:
        with self._lock:
            if self.state == HEALTHY:
                self._move(DEGRADED, "admission shedding")
            else:
                self._streak = 0

    def note_poison(self, cause: str = "batch parked on dead-letter"
                    ) -> None:
        with self._lock:
            if self.state != FAILED:
                self._move(DRAINING, cause)

    def note_fatal(self, cause: str = "unrecoverable step failure") -> None:
        with self._lock:
            self._move(FAILED, cause)

    def reset(self) -> None:
        with self._lock:
            self._move(HEALTHY, "operator reset")

    def recent_transitions(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def to_json(self) -> Dict:
        return {"state": self.state, "code": self.code,
                "transitions": self.transitions,
                "last_transition_ms": self.last_transition_ms,
                "last_cause": self.last_cause,
                "recent": self.recent_transitions()}
