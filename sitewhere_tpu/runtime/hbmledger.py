"""HBM residency ledger: what the pipeline keeps resident on the mesh.

The engines pin several tensor tables in device memory for the life of
the process — device state, rule state, anomaly-model state, compiled
rule tables, model weights, the registry param mirrors — plus bounded
per-step allocations (alert/route lanes, the staging-blob ring). Nothing
reported how much HBM each table holds, so capacity planning ("how many
more devices/rules fit this chip?") meant reading shapes out of source.

This module walks the engine's resident pytrees and computes the fixed
per-step capacities, returning a named byte ledger that exports as
``hbm.table_bytes{table="..."}`` gauges (runtime/metrics.py labeled
extra-gauges) and as the ``hbm`` block of ``GET /api/instance/topology``.
Everything here is host-side accounting over ``.nbytes`` — no device
sync, no fetch; safe on the telemetry path.

``device_headroom()`` adds the runtime's own view when the backend
exposes one (``Device.memory_stats()`` on TPU; absent on cpu) so the
ledger can be sanity-checked against actual ``bytes_in_use``.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax


def _tree_bytes(tree) -> int:
    """Total nbytes across a pytree's array leaves (0 for None). For
    sharded arrays this is the GLOBAL footprint — the ledger answers
    "what does this table cost the mesh", not one chip."""
    if tree is None:
        return 0
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def table_bytes(engine) -> Dict[str, int]:
    """Byte ledger of every resident table for one engine (single-chip
    PipelineEngine or ShardedPipelineEngine — the sharded state trees are
    global arrays, so the same walk covers both)."""
    from sitewhere_tpu.ops.compact import ALERT_LANE_ROWS
    from sitewhere_tpu.ops.pack import WIRE_ROWS

    params = getattr(engine, "_params", None)
    out: Dict[str, int] = {
        "device_state": _tree_bytes(getattr(engine, "_state", None)),
        # rule/model state are the fused i32 slabs ([D, P, 4*S+2] /
        # [D, P, 4*F+2], ops/stateful.py) plus their [P] counter rows —
        # the nbytes walk reports the slab layout directly
        "rule_state": _tree_bytes(getattr(engine, "_rule_state", None)),
        "model_state": _tree_bytes(getattr(engine, "_model_state", None)),
        "rule_tables": 0,
        "model_weights": 0,
        "registry_params": 0,
    }
    if params is not None:
        out["rule_tables"] = sum(
            _tree_bytes(getattr(params, k, None))
            for k in ("threshold", "zones", "geofence", "programs"))
        out["model_weights"] = _tree_bytes(getattr(params, "models", None))
        out["registry_params"] = sum(
            _tree_bytes(getattr(params, k, None))
            for k in ("assignment_status", "tenant_idx", "area_idx",
                      "device_type_idx"))
    # Fixed per-step capacities (allocated fresh each step but always the
    # same shape — they size the steady-state working set):
    shards = int(getattr(engine, "n_shards", 1) or 1)
    alert_cap = int(getattr(engine, "alert_lane_capacity", 0) or 0)
    out["alert_lanes"] = ALERT_LANE_ROWS * 4 * alert_cap * shards
    route_cap = int(getattr(engine, "route_lane_capacity", 0) or 0)
    # device-routing exchange lanes: [S, WIRE_ROWS, lane_cap] int32 per
    # shard pair exchanged inside the step (ops/route.py)
    out["route_lanes"] = WIRE_ROWS * 4 * route_cap * shards
    # staging-blob ring (host-pinned, counted because it sizes the H2D
    # working set; empty until first full-size accelerator submit)
    ring = getattr(engine, "_blob_ring", None)
    out["staging_buffers"] = (sum(int(b.nbytes) for b in ring)
                              if ring else 0)
    # on-device H2D staging ring (pipeline/staging.py): device arrays
    # currently parked in ring slots — the DEVICE-side counterpart of
    # staging_buffers, sizing the multi-buffered transfer working set
    # (h2d_buffer_depth in-flight blobs at steady state)
    dev_ring = getattr(engine, "_staging_ring", None)
    out["staging_ring"] = (int(dev_ring.resident_bytes())
                           if dev_ring is not None else 0)
    return out


def device_headroom() -> Optional[Dict[str, int]]:
    """The backend's own memory accounting for device 0 (None when the
    runtime doesn't expose memory_stats — cpu, some emulators)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {k: int(v) for k, v in stats.items()
           if isinstance(v, (int, float))}
    if "bytes_limit" in out and "bytes_in_use" in out:
        out["bytes_free"] = out["bytes_limit"] - out["bytes_in_use"]
    return out


def ledger(engine) -> Dict:
    """The full ledger block: per-table bytes, total, and (when the
    backend reports it) device headroom — the /api/instance/topology
    ``hbm`` payload."""
    tables = table_bytes(engine)
    out: Dict = {"tables": tables,
                 "total_bytes": int(sum(tables.values()))}
    headroom = device_headroom()
    if headroom is not None:
        out["device"] = headroom
    return out


def export_gauges(engine, prefix: str = "hbm.table_bytes") -> Dict[str, int]:
    """Labeled extra-gauge dict for MetricsRegistry.prometheus_text:
    one ``hbm.table_bytes{table="..."}`` sample per resident table plus
    the ``hbm.total_bytes`` rollup."""
    tables = table_bytes(engine)
    out = {f'{prefix}{{table="{name}"}}': bytes_
           for name, bytes_ in tables.items()}
    out["hbm.total_bytes"] = int(sum(tables.values()))
    return out
