"""Recovery epochs, write fencing, leased ownership, and the replay
output barrier — the cluster-grade recovery semantics layered over the
crash-safe artifacts from persist/.

Reference: the platform this reproduces leans on ZooKeeper for exactly
this job — ephemeral ownership znodes with monotonic zxid fencing so a
partitioned microservice that comes back cannot keep writing with
pre-partition state. Here the same three primitives are host-local and
explicit:

  epoch     a monotonic integer minted on every engine boot/takeover
            (durable in ``recovery-epoch.json`` under data_dir), stamped
            into checkpoint manifests, gossip/replication envelopes, and
            busnet RPCs
  fence     per-resource epoch floors; a write carrying an epoch below
            the floor is rejected with a counted StaleEpochError — the
            zombie/split-brain guard
  lease     TTL ownership renewed over the existing heartbeat edges;
            expiry (or a `failed` health ladder) triggers a takeover by
            the deterministic successor (lowest healthy peer rank)

The replay barrier makes checkpoint replay exactly-once in its
*effects*: the instance checkpoint captures per-tenant eventlog
high-watermarks, so on restore the rows already durable beyond the
checkpoint are a known per-tenant budget; while the budget lasts,
replayed inbound records rebuild device/rule/model state but are
suppressed from re-persisting and re-firing alert fan-out, command
delivery, and analytics increments (`replay.suppressed_effects`).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS

LOGGER = logging.getLogger("sitewhere.recovery")

EPOCH_FILE = "recovery-epoch.json"

# process-wide fallback when there is no data_dir (in-memory instances):
# still monotonic within the process, which is all a non-durable
# instance can promise anyway
_mem_epoch = 0
_mem_lock = threading.Lock()


class StaleEpochError(Exception):
    """A write carried an epoch below the fenced floor for its resource.

    Structured (resource/epoch/floor ride the exception) so receivers
    can reject without string-matching, and counted on
    ``fencing.rejected`` at every rejection site.
    """

    def __init__(self, resource: str, epoch: int, floor: int):
        super().__init__(
            f"stale epoch {epoch} < fenced floor {floor} for "
            f"'{resource}'")
        self.resource = resource
        self.epoch = epoch
        self.floor = floor


def stored_epoch(data_dir: Optional[str]) -> int:
    """Read the durable epoch without minting (0 when never minted)."""
    if not data_dir:
        return _mem_epoch
    path = os.path.join(data_dir, EPOCH_FILE)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return int(json.load(fh).get("epoch", 0))
    except (OSError, ValueError):
        return 0


def mint_epoch(data_dir: Optional[str]) -> int:
    """Mint the next recovery epoch: read, increment, fsync, rename.

    Called once per engine boot or takeover. Durable under data_dir so a
    restarted host always comes back ABOVE any floor it was fenced at
    (floor = last_seen + 1 == restarted mint), re-admitting it without
    operator action.
    """
    global _mem_epoch
    if not data_dir:
        with _mem_lock:
            _mem_epoch += 1
            return _mem_epoch
    os.makedirs(data_dir, exist_ok=True)
    epoch = stored_epoch(data_dir) + 1
    path = os.path.join(data_dir, EPOCH_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"epoch": epoch}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return epoch


class EpochFence:
    """Per-resource epoch floors. ``observe`` learns floors from traffic
    (a resource's own newer epoch fences its older incarnations);
    ``fence`` raises a floor explicitly (the takeover broadcast);
    ``check`` rejects stale writers with a counted StaleEpochError."""

    def __init__(self, metrics=GLOBAL_METRICS):
        self._floors: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._rejected = metrics.counter("fencing.rejected")

    def floor(self, resource: str) -> int:
        with self._lock:
            return self._floors.get(resource, 0)

    def observe(self, resource: str, epoch: int) -> None:
        """Learn: a resource's highest seen epoch becomes its floor."""
        with self._lock:
            if epoch > self._floors.get(resource, 0):
                self._floors[resource] = int(epoch)

    def fence(self, resource: str, epoch: int) -> int:
        """Raise the floor to at least `epoch`; returns the floor."""
        with self._lock:
            floor = max(self._floors.get(resource, 0), int(epoch))
            self._floors[resource] = floor
        LOGGER.info("fenced '%s' at epoch %d", resource, floor)
        return floor

    def admit(self, resource: str, epoch: int) -> bool:
        """True when the write may proceed; counts rejections."""
        with self._lock:
            floor = self._floors.get(resource, 0)
            if epoch < floor:
                self._rejected.inc()
                return False
            if epoch > floor:
                self._floors[resource] = int(epoch)
            return True

    def check(self, resource: str, epoch: int) -> None:
        if not self.admit(resource, epoch):
            raise StaleEpochError(resource, epoch, self.floor(resource))

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._floors)

    @property
    def rejected(self) -> int:
        return self._rejected.value


@dataclass
class Lease:
    resource: str
    owner: str
    epoch: int
    ttl_s: float
    renewed_at: float  # monotonic seconds

    def expired(self, now: float) -> bool:
        return now - self.renewed_at > self.ttl_s

    def to_json(self, now: float) -> Dict:
        return {"resource": self.resource, "owner": self.owner,
                "epoch": self.epoch, "ttl_s": self.ttl_s,
                "age_s": round(now - self.renewed_at, 3),
                "expired": self.expired(now)}


class LeaseTable:
    """TTL ownership records judged on a monotonic clock (injectable for
    deterministic tests). Acquire succeeds against a free, expired, or
    own lease — or steals a live one only with a strictly higher epoch
    (the takeover path: the successor fenced the old epoch first, so the
    steal and the fence are one decision). Renewals are counted
    (`lease.renewals`) and only the current owner with a current-or-newer
    epoch renews, so two hosts can never both hold a live lease."""

    def __init__(self, metrics=GLOBAL_METRICS,
                 clock: Callable[[], float] = time.monotonic):
        self._leases: Dict[str, Lease] = {}
        self._lock = threading.Lock()
        self._clock = clock
        self._renewals = metrics.counter("lease.renewals")

    def acquire(self, resource: str, owner: str, epoch: int,
                ttl_s: float, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        with self._lock:
            lease = self._leases.get(resource)
            if (lease is not None and not lease.expired(now)
                    and lease.owner != owner and epoch <= lease.epoch):
                return False  # live lease held elsewhere, no fencing steal
            self._leases[resource] = Lease(resource, owner, int(epoch),
                                           float(ttl_s), now)
            return True

    def renew(self, resource: str, owner: str, epoch: int,
              now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        with self._lock:
            lease = self._leases.get(resource)
            if lease is None or lease.owner != owner \
                    or epoch < lease.epoch:
                return False
            lease.renewed_at = now
            lease.epoch = max(lease.epoch, int(epoch))
            self._renewals.inc()
            return True

    def release(self, resource: str, owner: str) -> bool:
        """Drop the lease if `owner` holds it (takeover handback when the
        original owner returns above its fenced floor)."""
        with self._lock:
            lease = self._leases.get(resource)
            if lease is None or lease.owner != owner:
                return False
            del self._leases[resource]
            return True

    def holder(self, resource: str,
               now: Optional[float] = None) -> Optional[str]:
        now = self._clock() if now is None else now
        with self._lock:
            lease = self._leases.get(resource)
            if lease is None or lease.expired(now):
                return None
            return lease.owner

    def expired(self, resource: str,
                now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        with self._lock:
            lease = self._leases.get(resource)
            return lease is not None and lease.expired(now)

    def get(self, resource: str) -> Optional[Lease]:
        with self._lock:
            return self._leases.get(resource)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Dict]:
        now = self._clock() if now is None else now
        with self._lock:
            return {r: lease.to_json(now)
                    for r, lease in self._leases.items()}


def elect_successor(healthy_by_rank: Dict[int, bool],
                    exclude: Optional[int] = None) -> Optional[int]:
    """Deterministic successor: the lowest healthy peer rank. Every host
    computes the same answer from the same health view, so no election
    round-trip is needed — at most one host believes it is the
    successor."""
    candidates = sorted(rank for rank, healthy in healthy_by_rank.items()
                        if healthy and rank != exclude)
    return candidates[0] if candidates else None


class ReplayBarrier:
    """Output barrier for checkpoint replay: per-tenant budgets of rows
    already durable beyond the restored checkpoint. While a tenant's
    budget lasts, replayed inbound records rebuild state but are
    suppressed from re-persisting and re-firing effects — `take`
    consumes budget and counts `replay.suppressed_effects`. Disarmed
    (`active()` False) the hot-path check is one dict read under no
    contention."""

    def __init__(self, metrics=GLOBAL_METRICS):
        self._budgets: Dict[str, int] = {}
        self._marks: Dict[str, Dict[str, int]] = {}
        self._lock = threading.Lock()
        self._armed = False
        self._suppressed = metrics.counter("replay.suppressed_effects")

    def arm(self, budgets: Dict[str, int],
            watermarks: Optional[Dict[str, Dict[str, int]]] = None) -> None:
        with self._lock:
            self._budgets = {t: int(n) for t, n in budgets.items()
                             if int(n) > 0}
            # the per-tenant (id_prefix -> max id_seq) watermarks behind
            # the budgets: the straggler deduplicator seeds from these
            self._marks = {t: dict(m)
                           for t, m in (watermarks or {}).items()}
            self._armed = bool(self._budgets)
        if self._armed:
            LOGGER.info("replay barrier armed: %s", self._budgets)

    def disarm(self) -> None:
        with self._lock:
            self._budgets = {}
            self._marks = {}
            self._armed = False

    def watermarks(self, tenant: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._marks.get(tenant, {}))

    def active(self, tenant: Optional[str] = None) -> bool:
        if not self._armed:
            return False
        with self._lock:
            if tenant is None:
                return bool(self._budgets)
            return self._budgets.get(tenant, 0) > 0

    def remaining(self, tenant: str) -> int:
        with self._lock:
            return self._budgets.get(tenant, 0)

    def take(self, tenant: str, n: int) -> int:
        """Consume up to `n` rows of the tenant's budget; returns how
        many of the `n` are replay duplicates to suppress."""
        if not self._armed or n <= 0:
            return 0
        with self._lock:
            budget = self._budgets.get(tenant, 0)
            if budget <= 0:
                return 0
            took = min(budget, int(n))
            left = budget - took
            if left:
                self._budgets[tenant] = left
            else:
                del self._budgets[tenant]
                if not self._budgets:
                    self._armed = False
        self._suppressed.inc(took)
        return took

    @property
    def suppressed(self) -> int:
        return self._suppressed.value


# module singletons, mirroring GLOBAL_METRICS / GLOBAL_ADMISSION: the
# inbound hot path and the checkpoint manager must agree on one barrier
# without threading it through every constructor
GLOBAL_REPLAY_BARRIER = ReplayBarrier()
GLOBAL_FENCE = EpochFence()

# checkpointed AlternateIdDeduplicator windows, stashed at boot restore
# and claimed when each event source starts: restore_on_boot runs before
# tenant engines exist (and sources are registered even later), so the
# hand-off has to cross that lifecycle gap
_dedup_seeds: Dict[tuple, list] = {}
_seed_lock = threading.Lock()


def stash_dedup_seeds(windows: Dict[str, Dict[str, list]]) -> None:
    """Stage `{tenant: {source_id: [alternate ids, oldest first]}}` for
    event sources that have not started yet."""
    with _seed_lock:
        for tenant, per_source in (windows or {}).items():
            for source_id, ids in (per_source or {}).items():
                _dedup_seeds[(str(tenant), str(source_id))] = list(ids)


def take_dedup_seed(tenant: str, source_id: str) -> Optional[list]:
    """Claim (pop) a staged window; None when nothing was checkpointed."""
    with _seed_lock:
        return _dedup_seeds.pop((str(tenant), str(source_id)), None)
