"""Script management: named, versioned user scripts with live activation.

Reference: the Groovy scripting stack — GroovyComponent.java:32 (script
host), ScriptSynchronizer.java (ZK -> local-disk sync),
ZookeeperScriptManagement.java (versioned script storage), and the REST
surface at Instance.java:304-560 (create/list scripts, versioned content,
clone, activate, delete; global and per-tenant scopes).

The TPU rebuild keeps the shape but swaps Groovy for Python source: a script
is versioned text whose ACTIVE version is compiled into a module namespace;
`resolve(scope, id, entry)` hands components a stable proxy callable that
always dispatches to the active version, so activating a new version
hot-swaps behavior without rebinding decoders/connectors (the reference
restarts components on ZK script-change events; the proxy makes that
unnecessary). With a data_dir, scripts sync to disk as .py + meta.json and
reload on start (the ScriptSynchronizer role).

Scripts are an operator extension point: like Groovy in the reference they
execute with full interpreter privileges — deployment trust model, not a
sandbox.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.common import now_ms
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

GLOBAL_SCOPE = "global"
LOGGER = logging.getLogger("sitewhere.scripts")
# filesystem- and route-safe: single path segment, no traversal
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


@dataclass
class ScriptVersion:
    version_id: str
    comment: str = ""
    created_ms: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {"versionId": self.version_id, "comment": self.comment,
                "createdDate": self.created_ms}


@dataclass
class ScriptInfo:
    script_id: str
    name: str = ""
    description: str = ""
    active_version: Optional[str] = None
    versions: List[ScriptVersion] = field(default_factory=list)
    # last-writer-wins stamp for cross-host replication (cluster gossip);
    # bumped on every mutation, adopted from the winner on apply
    updated_ms: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {"scriptId": self.script_id, "name": self.name,
                "description": self.description,
                "activeVersion": self.active_version,
                "updatedMs": self.updated_ms,
                "versions": [v.to_json() for v in self.versions]}


class _ScriptProxy:
    """Stable callable bound to (manager, scope, script_id, entry): always
    dispatches to the active version's compiled namespace."""

    def __init__(self, manager: "ScriptManager", scope: str, script_id: str,
                 entry: str):
        self._m = manager
        self._key = (scope, script_id)
        self._entry = entry

    def __call__(self, *args, **kwargs):
        fn = self._m._active_entry(self._key, self._entry)
        return fn(*args, **kwargs)


class ScriptManager(LifecycleComponent):
    """Versioned script registry, scoped (GLOBAL_SCOPE or a tenant token)."""

    def __init__(self, data_dir: Optional[str] = None):
        super().__init__("script-manager")
        self._data_dir = data_dir
        self._lock = threading.RLock()
        # (scope, script_id) -> ScriptInfo
        self._scripts: Dict[tuple, ScriptInfo] = {}
        # (scope, script_id, version_id) -> source text
        self._content: Dict[tuple, str] = {}
        # (scope, script_id) -> compiled namespace of the active version
        self._namespaces: Dict[tuple, Dict[str, Any]] = {}
        # (scope, script_id) -> deletion stamp: an upsert older than the
        # tombstone stays dead; a NEWER one resurrects (same contract as
        # the registry gossip tombstones, parallel/cluster.py). DURABLE
        # (tombstones.json): a checkpoint restore or a post-restart gossip
        # redelivery replays stale upserts, and without the persisted
        # stamp a deleted script would come back on this host alone.
        self._tombstones: Dict[tuple, int] = {}
        # mutation listeners: fn(op: "upsert"|"delete", scope, script_id,
        # state_or_stamp) — called AFTER the mutation, outside the lock
        # (cluster gossip replicates through this)
        self._listeners: List[Callable] = []

    # -- lifecycle / disk sync ---------------------------------------------

    def on_start(self, monitor) -> None:
        if self._data_dir:
            self._load_tombstones()
            self._load_from_disk()

    def _tombstones_path(self) -> str:
        return os.path.join(self._data_dir, "scripts", "tombstones.json")

    def _load_tombstones(self) -> None:
        path = self._tombstones_path()
        if not os.path.exists(path):
            return
        try:
            with open(path, encoding="utf-8") as fh:
                rows = json.load(fh)
            for row in rows:
                self._tombstones[(row["scope"], row["scriptId"])] = int(
                    row.get("stamp", 0))
        except (OSError, ValueError, TypeError, KeyError):
            # corrupt tombstones must not block startup (same contract as
            # _load_from_disk for corrupt script dirs)
            LOGGER.exception("unreadable script tombstones %s", path)

    def _sync_tombstones_locked(self) -> None:
        if not self._data_dir:
            return
        rows = [{"scope": s, "scriptId": sid, "stamp": stamp}
                for (s, sid), stamp in sorted(self._tombstones.items())]
        os.makedirs(os.path.join(self._data_dir, "scripts"), exist_ok=True)
        self._atomic_write(self._tombstones_path(), json.dumps(rows))

    def _scope_dir(self, scope: str) -> str:
        # Percent-encode: collision-free for arbitrary scopes ("a/b" vs
        # "a_b" previously mapped to the same directory and one scope's
        # meta.json silently overwrote the other's). Reload is unaffected
        # either way — meta.json records the true scope.
        from urllib.parse import quote
        return os.path.join(self._data_dir, "scripts",
                            quote(scope, safe=""))

    def _sync_to_disk(self, scope: str, info: ScriptInfo) -> None:
        if not self._data_dir:
            return
        d = os.path.join(self._scope_dir(scope), info.script_id)
        os.makedirs(d, exist_ok=True)
        # versions first, meta last, each atomically: a crash can leave
        # stray .py files but never a meta.json naming a missing version.
        # Always rewrite — apply_replicated can REPLACE content under an
        # existing version id (per-host version counters collide), and a
        # skip-if-exists here would persist the losing content, diverging
        # hosts after the next restart.
        for v in info.versions:
            path = os.path.join(d, f"{v.version_id}.py")
            self._atomic_write(
                path, self._content[(scope, info.script_id, v.version_id)])
        # drop version files the winning state no longer names
        keep = {f"{v.version_id}.py" for v in info.versions} | {"meta.json"}
        for name in os.listdir(d):
            if name.endswith(".py") and name not in keep:
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass
        self._atomic_write(os.path.join(d, "meta.json"),
                           json.dumps({"scope": scope, **info.to_json()}))

    @staticmethod
    def _atomic_write(path: str, content: str) -> None:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            fh.write(content)
        os.replace(tmp, path)

    def _load_from_disk(self) -> None:
        from urllib.parse import quote

        root = os.path.join(self._data_dir, "scripts")
        if not os.path.isdir(root):
            return
        # Canonical scope dirs (percent-encoded) load AFTER legacy ones
        # (pre-encoding underscore-replacement), so on a (scope, script_id)
        # conflict the canonical copy wins; legacy dirs then migrate.
        entries = []
        for scope_name in os.listdir(root):
            scope_dir = os.path.join(root, scope_name)
            if not os.path.isdir(scope_dir):
                continue  # tombstones.json lives beside the scope dirs
            for script_id in os.listdir(scope_dir):
                entries.append((scope_name, scope_dir, script_id))
        loaded = []
        for scope_name, scope_dir, script_id in sorted(
                entries, key=lambda e: self._is_canonical_dir(
                    e[0], e[1], e[2])):
            try:
                scope = self._load_one(scope_name, scope_dir, script_id)
                if scope is not None:
                    loaded.append((scope_name, scope_dir, script_id, scope))
            except Exception:
                # one corrupt script directory must not block startup
                LOGGER.exception("skipping unreadable script %s/%s",
                                 scope_name, script_id)
        # migrate legacy-named dirs to the canonical encoding
        import shutil
        for scope_name, scope_dir, script_id, scope in loaded:
            if scope_name == quote(scope, safe=""):
                continue
            try:
                info = self._scripts.get((scope, script_id))
                if info is not None:
                    self._sync_to_disk(scope, info)
                shutil.rmtree(os.path.join(scope_dir, script_id))
                if not os.listdir(scope_dir):
                    os.rmdir(scope_dir)
                LOGGER.info("migrated script dir %s/%s to canonical "
                            "scope encoding", scope_name, script_id)
            except OSError:
                LOGGER.exception("could not migrate legacy script dir "
                                 "%s/%s", scope_name, script_id)

    @staticmethod
    def _is_canonical_dir(scope_name: str, scope_dir: str,
                          script_id: str) -> bool:
        from urllib.parse import quote

        meta_path = os.path.join(scope_dir, script_id, "meta.json")
        try:
            with open(meta_path) as fh:
                scope = json.load(fh).get("scope", scope_name)
        except (OSError, ValueError):
            return False
        return scope_name == quote(scope, safe="")

    def _load_one(self, scope_name: str, scope_dir: str,
                  script_id: str) -> Optional[str]:
        """Returns the script's true scope, or None if nothing loaded."""
        meta_path = os.path.join(scope_dir, script_id, "meta.json")
        if not os.path.exists(meta_path):
            return None
        with open(meta_path) as fh:
            meta = json.load(fh)
        scope = meta.get("scope", scope_name)
        # a crash between tombstone persist and file removal leaves both:
        # the tombstone outranks the stale files, finish the delete here
        tomb = self._tombstones.get((scope, meta["scriptId"]), -1)
        if int(meta.get("updatedMs", 0)) <= tomb:
            import shutil
            shutil.rmtree(os.path.join(scope_dir, script_id),
                          ignore_errors=True)
            return None
        info = ScriptInfo(
            script_id=meta["scriptId"], name=meta.get("name", ""),
            description=meta.get("description", ""),
            active_version=meta.get("activeVersion"),
            updated_ms=meta.get("updatedMs", 0),
            versions=[ScriptVersion(v["versionId"], v.get("comment", ""),
                                    v.get("createdDate", 0))
                      for v in meta.get("versions", [])])
        key = (scope, info.script_id)
        for v in info.versions:
            path = os.path.join(scope_dir, script_id, f"{v.version_id}.py")
            with open(path) as fh:
                self._content[key + (v.version_id,)] = fh.read()
        if info.active_version:
            self._compile(key, info.active_version)
        self._scripts[key] = info  # registered only after a clean load
        return scope

    # -- replication surface ------------------------------------------------

    def add_listener(self, fn: Callable) -> None:
        """Register a mutation listener `fn(op, scope, script_id, payload)`
        — op "upsert" carries the full exported script state, op "delete"
        carries the tombstone stamp. Fired after every LOCAL mutation
        (apply_replicated/apply_delete do NOT fire it: appliers are the
        receive side)."""
        self._listeners.append(fn)

    def _notify(self, op: str, scope: str, script_id: str, payload) -> None:
        for fn in list(self._listeners):
            try:
                fn(op, scope, script_id, payload)
            except Exception:
                LOGGER.exception("script listener failed for %s %s/%s",
                                 op, scope, script_id)

    def export_script(self, scope: str, script_id: str) -> Dict[str, Any]:
        """Full replicable state of one script: metadata + every version's
        content. Scripts are small text; whole-state transfer keeps the
        applier idempotent and order-free (same reasoning as the registry
        gossip's by-token entity payloads)."""
        with self._lock:
            info = self.get_script(scope, script_id)
            return {"scope": scope, **info.to_json(),
                    "contents": {v.version_id:
                                 self._content[(scope, script_id,
                                                v.version_id)]
                                 for v in info.versions}}

    def export_state(self) -> List[Dict[str, Any]]:
        """Every script's exported state (instance checkpoint payload)."""
        with self._lock:
            return [self.export_script(scope, script_id)
                    for (scope, script_id) in sorted(self._scripts)]

    @staticmethod
    def _state_digest(state: Dict[str, Any]) -> str:
        import hashlib

        blob = json.dumps({k: v for k, v in state.items()
                           if k != "updatedMs"}, sort_keys=True)
        return hashlib.sha1(blob.encode()).hexdigest()

    def _lww_key(self, key: tuple) -> tuple:
        info = self._scripts.get(key)
        if info is None:
            return (self._tombstones.get(key, -1), "")
        return (info.updated_ms,
                self._state_digest(self.export_script(*key)))

    def apply_replicated(self, state: Dict[str, Any]) -> bool:
        """Upsert a replicated script if it wins last-writer-wins against
        the local copy (stamp, then host-independent digest — every host
        compares the same keys and picks the same winner). Idempotent;
        never fires listeners. Returns True when applied."""
        scope, script_id = state["scope"], state["scriptId"]
        # same path-safety contract as create_script: the id becomes a
        # filesystem component in _sync_to_disk, and a replicated payload
        # must not be able to write (or later rmtree) outside the scope
        # directory
        if not _ID_RE.match(script_id):
            raise SiteWhereError(
                f"replicated script id {script_id!r} invalid: must match "
                f"{_ID_RE.pattern}", http_status=400)
        incoming = (int(state.get("updatedMs", 0)),
                    self._state_digest(state))
        with self._lock:
            key = (scope, script_id)
            if incoming[0] <= self._tombstones.get(key, -1):
                return False  # deleted with a newer stamp: stays dead
            if self._scripts.get(key) is not None \
                    and incoming <= self._lww_key(key):
                return False
            info = ScriptInfo(
                script_id=script_id, name=state.get("name", ""),
                description=state.get("description", ""),
                active_version=state.get("activeVersion"),
                updated_ms=incoming[0],
                versions=[ScriptVersion(v["versionId"],
                                        v.get("comment", ""),
                                        v.get("createdDate", 0))
                          for v in state.get("versions", [])])
            # stage content + compile BEFORE replacing the local copy so a
            # broken payload cannot take down a working script
            contents = dict(state.get("contents", {}))
            for v in info.versions:
                if v.version_id not in contents:
                    raise SiteWhereError(
                        f"replicated script '{script_id}' missing content "
                        f"for {v.version_id}", http_status=400)
            old_content = {k: v for k, v in self._content.items()
                           if k[:2] == key}
            # the winner's version set REPLACES the local one: drop every
            # old content key first so versions absent from the winning
            # state don't linger readable through get_content
            for k in old_content:
                del self._content[k]
            for vid, text in contents.items():
                self._content[key + (vid,)] = text
            try:
                if info.active_version:
                    self._compile(key, info.active_version)
                else:
                    self._namespaces.pop(key, None)
            except Exception:
                for k in [k for k in self._content if k[:2] == key]:
                    del self._content[k]
                self._content.update(old_content)
                raise
            self._scripts[key] = info
            if self._tombstones.pop(key, None) is not None:
                self._sync_tombstones_locked()
            self._sync_to_disk(scope, info)
            return True

    def apply_delete(self, scope: str, script_id: str, stamp: int) -> bool:
        """Replicated deletion: applies when the local copy is not newer;
        always records the tombstone. Never fires listeners."""
        with self._lock:
            key = (scope, script_id)
            info = self._scripts.get(key)
            if info is not None and info.updated_ms > stamp:
                return False  # local write is newer: delete loses
            self._tombstones[key] = max(stamp,
                                        self._tombstones.get(key, -1))
            self._sync_tombstones_locked()
            if info is None:
                return False
            self._delete_locked(scope, script_id)
            return True

    # -- CRUD ---------------------------------------------------------------

    def create_script(self, scope: str, script_id: str, content: str,
                      name: str = "", description: str = "",
                      activate: bool = True) -> ScriptInfo:
        if not _ID_RE.match(script_id):
            raise SiteWhereError(
                f"invalid script id {script_id!r}: must match "
                f"{_ID_RE.pattern}", http_status=400)
        with self._lock:
            key = (scope, script_id)
            if key in self._scripts:
                raise SiteWhereError(f"script '{script_id}' already exists",
                                     ErrorCode.DUPLICATE_TOKEN)
            if activate:
                self._check_compiles(key, content)  # before registering
            # stamp PAST any local tombstone (delete-then-recreate in the
            # same millisecond must still replicate) and clear it
            info = ScriptInfo(script_id=script_id, name=name or script_id,
                              description=description,
                              updated_ms=max(now_ms(),
                                             self._tombstones.get(key, -1)
                                             + 1))
            if self._tombstones.pop(key, None) is not None:
                self._sync_tombstones_locked()
            self._scripts[key] = info
            version = self._add_version_locked(key, content, "initial")
            if activate:
                self._activate_locked(key, version.version_id)
            self._sync_to_disk(scope, info)
        self._notify("upsert", scope, script_id,
                     self.export_script(scope, script_id))
        return info

    def list_scripts(self, scope: str) -> List[ScriptInfo]:
        with self._lock:
            return [i for (s, _), i in sorted(self._scripts.items())
                    if s == scope]

    def get_script(self, scope: str, script_id: str) -> ScriptInfo:
        info = self._scripts.get((scope, script_id))
        if info is None:
            raise SiteWhereError(f"unknown script '{script_id}'",
                                 ErrorCode.GENERIC, http_status=404)
        return info

    def delete_script(self, scope: str, script_id: str) -> None:
        with self._lock:
            info = self.get_script(scope, script_id)
            key = (scope, script_id)
            # stamp past the script's last write so a concurrent remote
            # update with an older stamp cannot resurrect it
            stamp = max(now_ms(), info.updated_ms + 1)
            self._tombstones[key] = stamp
            # tombstone durable BEFORE the files go: a crash in between
            # leaves dir + tombstone, which _load_one reconciles at boot
            self._sync_tombstones_locked()
            self._delete_locked(scope, script_id)
        self._notify("delete", scope, script_id, stamp)

    def _delete_locked(self, scope: str, script_id: str) -> None:
        key = (scope, script_id)
        info = self._scripts.pop(key)
        self._namespaces.pop(key, None)
        for v in info.versions:
            self._content.pop(key + (v.version_id,), None)
        if self._data_dir:
            d = os.path.join(self._scope_dir(scope), script_id)
            if os.path.isdir(d):
                for f in os.listdir(d):
                    os.unlink(os.path.join(d, f))
                os.rmdir(d)

    # -- versions -----------------------------------------------------------

    def _add_version_locked(self, key: tuple, content: str,
                            comment: str) -> ScriptVersion:
        info = self._scripts[key]
        version = ScriptVersion(
            version_id=f"v{len(info.versions) + 1}", comment=comment,
            created_ms=now_ms())
        info.versions.append(version)
        self._content[key + (version.version_id,)] = content
        return version

    def add_version(self, scope: str, script_id: str, content: str,
                    comment: str = "", activate: bool = False
                    ) -> ScriptVersion:
        with self._lock:
            info = self.get_script(scope, script_id)
            key = (scope, script_id)
            version = self._add_version_locked(key, content, comment)
            if activate:
                self._activate_locked(key, version.version_id)
            # monotonic past the previous write: same-millisecond
            # mutations must still order under last-writer-wins
            info.updated_ms = max(now_ms(), info.updated_ms + 1)
            self._sync_to_disk(scope, info)
        self._notify("upsert", scope, script_id,
                     self.export_script(scope, script_id))
        return version

    def clone_version(self, scope: str, script_id: str, version_id: str,
                      comment: str = "") -> ScriptVersion:
        # read under the manager's internal locking, then delegate OUTSIDE
        # any held lock: add_version's listener notification does network
        # publishes in a cluster and must not run under self._lock
        content = self.get_content(scope, script_id, version_id)
        return self.add_version(scope, script_id, content,
                                comment or f"clone of {version_id}")

    def get_content(self, scope: str, script_id: str,
                    version_id: Optional[str] = None) -> str:
        info = self.get_script(scope, script_id)
        vid = version_id or info.active_version
        content = self._content.get((scope, script_id, vid))
        if content is None:
            raise SiteWhereError(f"unknown version '{vid}'",
                                 ErrorCode.GENERIC, http_status=404)
        return content

    # -- activation / execution --------------------------------------------

    @staticmethod
    def _check_compiles(key: tuple, source: str) -> None:
        try:
            compile(source, f"<script {key[1]}>", "exec")
        except SyntaxError as exc:
            raise SiteWhereError(f"script does not compile: {exc}",
                                 http_status=400) from exc

    def _compile(self, key: tuple, version_id: str) -> Dict[str, Any]:
        source = self._content[key + (version_id,)]
        namespace: Dict[str, Any] = {"__name__":
                                     f"swtpu_script_{key[1]}_{version_id}"}
        try:
            code = compile(source, f"<script {key[1]}:{version_id}>", "exec")
            exec(code, namespace)  # operator extension point (see module doc)
        except SiteWhereError:
            raise
        except Exception as exc:
            raise SiteWhereError(
                f"script '{key[1]}:{version_id}' failed to load: {exc}",
                http_status=400) from exc
        self._namespaces[key] = namespace
        return namespace

    def _activate_locked(self, key: tuple, version_id: str) -> None:
        info = self._scripts[key]
        if version_id not in {v.version_id for v in info.versions}:
            raise SiteWhereError(f"unknown version '{version_id}'",
                                 ErrorCode.GENERIC, http_status=404)
        self._compile(key, version_id)  # compile FIRST: bad scripts do not
        info.active_version = version_id  # replace a working active version

    def activate_version(self, scope: str, script_id: str,
                         version_id: str) -> ScriptInfo:
        with self._lock:
            info = self.get_script(scope, script_id)
            self._activate_locked((scope, script_id), version_id)
            info.updated_ms = max(now_ms(), info.updated_ms + 1)
            self._sync_to_disk(scope, info)
        self._notify("upsert", scope, script_id,
                     self.export_script(scope, script_id))
        return info

    def _active_entry(self, key: tuple, entry: str) -> Callable:
        ns = self._namespaces.get(key)
        if ns is None:
            raise SiteWhereError(
                f"script '{key[1]}' has no active version", ErrorCode.GENERIC)
        fn = ns.get(entry)
        if not callable(fn):
            raise SiteWhereError(
                f"script '{key[1]}' defines no callable '{entry}'",
                ErrorCode.GENERIC)
        return fn

    def resolve(self, scope: str, script_id: str, entry: str,
                require_entry: bool = False) -> Callable:
        """A stable callable dispatching to the ACTIVE version's `entry`
        function — survives later activations (hot swap).
        ``require_entry`` additionally fail-fasts when the CURRENT active
        version does not define a callable `entry` (callers installing
        long-lived consumers want a 4xx at install time, not a silently
        dead component)."""
        self.get_script(scope, script_id)  # fail fast on unknown id
        if require_entry:
            self._active_entry((scope, script_id), entry)
        return _ScriptProxy(self, scope, script_id, entry)
