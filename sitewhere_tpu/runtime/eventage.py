"""End-to-end event-age accounting: ingest edge -> effect edge.

The flight recorder (runtime/flight.py) attributes *step wall* by stage;
this module measures the other axis of the paper's 10 ms p99 target —
how long an event existed before its effect landed: receiver queueing,
batcher linger, feeder turnstile wait, dispatch, lane fetch, and
materialization all fold into one number per event.

Receivers stamp one monotonic ``received_at`` (``time.perf_counter()``,
the flight recorder's clock) per *delivery* — a payload of N decoded
events shares one stamp, so the hot path never builds a per-row host
array.  The batcher/feeder folds ``(stamp, n)`` pairs into an
:class:`AgeSidecar` that rides the step's flight record through every
cross-thread handoff (``_PreparedStep.flight``, the feeder heap tuples)
on both engine kinds.  At a close edge (materialize / alert emission /
persist) the sidecar resolves into an :class:`AgeSummary` — count, sum,
min, max, and fixed log2 bucket counts — which feeds the labeled
``pipeline.event_age_seconds`` Prometheus histogram and the flight
export's derived p50/p99.

Hot-path budget: ``add`` is an append (amortized; bounded by
``AGE_MAX_ENTRIES`` with a deterministic weighted-merge spill), a close
is O(entries) and runs on the materialize path that already does
O(alerts) host work.  perf_gate's ``telemetry_overhead`` check pins the
whole plane under 1% of step wall.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Fixed log2 age buckets. Bucket 0 counts ages <= AGE_BUCKET_FLOOR_S;
# bucket k (k >= 1) counts ages in (floor * 2^(k-1), floor * 2^k]; the
# last bucket is open-ended. 0.1 ms * 2^18 ≈ 26 s of dynamic range —
# anything older is an incident, not a latency distribution.
AGE_BUCKET_FLOOR_S = 1e-4
N_AGE_BUCKETS = 20

# Upper bucket edges in seconds (finite edges only; the last bucket is
# +Inf). These double as the Prometheus histogram bucket bounds so the
# flight rollup and the scraped histogram bucket identically.
AGE_BUCKET_EDGES_S: Tuple[float, ...] = tuple(
    AGE_BUCKET_FLOOR_S * (2.0 ** k) for k in range(N_AGE_BUCKETS - 1))

# A sidecar never grows past this many delivery entries: the batcher can
# fold hundreds of tiny deliveries into one batch, and the sidecar must
# stay O(1)-ish however the traffic arrives. On overflow the NEWEST two
# entries merge (weighted-mean stamp — exact for sum/mean, conservative
# for min/max since merged stamps stay inside [min, max]).
AGE_MAX_ENTRIES = 64


def bucket_index(age_s: float) -> int:
    """Bucket index for one age (seconds). The oracle test mirrors this
    exact formula in NumPy — keep them in lockstep."""
    if age_s <= AGE_BUCKET_FLOOR_S:
        return 0
    idx = int(math.floor(math.log2(age_s / AGE_BUCKET_FLOOR_S))) + 1
    return idx if idx < N_AGE_BUCKETS else N_AGE_BUCKETS - 1


class AgeSummary:
    """Closed per-batch age digest: count/sum/min/max + log2 buckets."""

    __slots__ = ("count", "sum_s", "min_s", "max_s", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0
        self.buckets: List[int] = [0] * N_AGE_BUCKETS

    def fold(self, age_s: float, n: int) -> None:
        age_s = max(0.0, age_s)
        self.count += n
        self.sum_s += age_s * n
        if age_s < self.min_s:
            self.min_s = age_s
        if age_s > self.max_s:
            self.max_s = age_s
        self.buckets[bucket_index(age_s)] += n

    def merge(self, other: "AgeSummary") -> None:
        self.count += other.count
        self.sum_s += other.sum_s
        self.min_s = min(self.min_s, other.min_s)
        self.max_s = max(self.max_s, other.max_s)
        for i in range(N_AGE_BUCKETS):
            self.buckets[i] += other.buckets[i]

    def quantile_s(self, q: float) -> float:
        """Bucketed quantile estimate: the upper edge of the bucket the
        rank lands in (an upper bound; the last bucket reports the max
        observed age since it has no finite edge)."""
        if self.count <= 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, b in enumerate(self.buckets):
            acc += b
            if b > 0 and acc >= rank:
                if i < len(AGE_BUCKET_EDGES_S):
                    return min(AGE_BUCKET_EDGES_S[i], self.max_s)
                return self.max_s
        return self.max_s

    def export(self) -> Dict:
        if self.count <= 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean_ms": round(self.sum_s / self.count * 1e3, 4),
            "min_ms": round(self.min_s * 1e3, 4),
            "max_ms": round(self.max_s * 1e3, 4),
            "p50_ms": round(self.quantile_s(0.50) * 1e3, 4),
            "p99_ms": round(self.quantile_s(0.99) * 1e3, 4),
            "buckets": list(self.buckets),
        }


class AgeSidecar:
    """Open per-batch age carrier: bounded ``(stamp, n)`` delivery
    entries. Travels on ``StepRecord.age`` through the feeder/engine
    handoffs; closed (pure — close never mutates, so materialize, alert
    emission, and persist can each close the same sidecar at their own
    instant) into an :class:`AgeSummary`."""

    __slots__ = ("entries",)

    def __init__(self) -> None:
        self.entries: List[List[float]] = []  # [stamp_s, n]

    def add(self, stamp_s: Optional[float], n: int) -> None:
        if n <= 0:
            return
        if stamp_s is None:
            stamp_s = time.perf_counter()
        entries = self.entries
        if len(entries) >= AGE_MAX_ENTRIES:
            # deterministic spill: merge the two newest entries by
            # event-weighted mean stamp (exact sum/mean, bounded error
            # on min/max/buckets only for pathological delivery storms)
            last = entries[-1]
            total = last[1] + n
            last[0] = (last[0] * last[1] + stamp_s * n) / total
            last[1] = total
            return
        entries.append([stamp_s, float(n)])

    def merge(self, other: Optional["AgeSidecar"]) -> None:
        if other is None:
            return
        for stamp, n in other.entries:
            self.add(stamp, int(n))

    @property
    def count(self) -> int:
        return int(sum(n for _, n in self.entries))

    def close(self, now_s: Optional[float] = None) -> AgeSummary:
        if now_s is None:
            now_s = time.perf_counter()
        summary = AgeSummary()
        for stamp, n in self.entries:
            summary.fold(now_s - stamp, int(n))
        return summary


def sidecar_to_wire(sidecar: Optional[AgeSidecar],
                    now_s: Optional[float] = None) -> List[List[float]]:
    """Sidecar entries for cross-process transport. ``perf_counter``
    stamps are process-local — they must never cross a process boundary
    raw. The wire form carries AGE-SO-FAR per entry ([age_s, n]); the
    receiver re-stamps against its own clock (:func:`sidecar_from_wire`),
    so the end-to-end age keeps accumulating across the hop and only the
    one-way transport skew (not clock-domain garbage) is lost."""
    if sidecar is None or not sidecar.entries:
        return []
    if now_s is None:
        now_s = time.perf_counter()
    return [[max(0.0, now_s - stamp), n] for stamp, n in sidecar.entries]


def sidecar_from_wire(entries: Sequence[Sequence[float]],
                      now_s: Optional[float] = None) -> AgeSidecar:
    """Rebuild a sidecar from wire age-so-far entries, re-stamped on the
    receiving process's ``perf_counter`` clock."""
    if now_s is None:
        now_s = time.perf_counter()
    sidecar = AgeSidecar()
    for age_s, n in entries:
        sidecar.add(now_s - max(0.0, float(age_s)), int(n))
    return sidecar


def observe_summary(hist, summary: AgeSummary, **labels) -> None:
    """Feed a closed summary into a bucketed Prometheus histogram whose
    buckets are AGE_BUCKET_EDGES_S (runtime/metrics.py Histogram built
    by :func:`age_histogram`): bucket counts transfer 1:1, sum/count
    stay exact."""
    if summary.count <= 0:
        return
    hist.observe_buckets(summary.buckets, summary.sum_s, summary.count,
                         **labels)


def age_histogram(registry):
    """The shared ingest->effect age histogram (labels: engine, edge)."""
    return registry.histogram("pipeline.event_age_seconds",
                              buckets=AGE_BUCKET_EDGES_S)
