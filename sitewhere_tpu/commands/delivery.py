"""Command delivery service: enriched command invocations -> devices.

Reference call stack (SURVEY.md §3.4): EnrichedCommandInvocationsConsumer ->
DefaultCommandProcessingStrategy (resolve IDeviceCommand, build execution) ->
CommandRoutingLogic / target resolution -> OutboundCommandRouter ->
CommandDestination (encode + extract params + deliver). Failures land on the
undelivered-command-invocations topic (KafkaTopicNaming.java:69).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from sitewhere_tpu.commands.destinations import CommandDestination
from sitewhere_tpu.commands.encoding import (
    CommandExecution, SystemCommand, coerce_parameters)
from sitewhere_tpu.commands.routing import CommandRouter, SingleDestinationRouter
from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.model.device import Device, DeviceAssignment
from sitewhere_tpu.model.event import CommandTarget, DeviceCommandInvocation
from sitewhere_tpu.pipeline.enrichment import unpack_enriched
from sitewhere_tpu.runtime.bus import ConsumerHost, EventBus, Record, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

LOGGER = logging.getLogger("sitewhere.commands")


class CommandProcessingStrategy:
    """Resolve the invocation into an executable command
    (DefaultCommandProcessingStrategy.java)."""

    def __init__(self, registry):
        self.registry = registry

    def create_execution(self, invocation: DeviceCommandInvocation
                         ) -> CommandExecution:
        command = None
        if invocation.command_token:
            command = self.registry.device_commands.get_by_token(
                invocation.command_token)
        if command is None and invocation.device_command_id:
            command = self.registry.device_commands.get(
                invocation.device_command_id)
        if command is None:
            raise SiteWhereError(
                f"invocation references unknown command "
                f"'{invocation.command_token or invocation.device_command_id}'")
        parameters = coerce_parameters(command, invocation.parameter_values)
        return CommandExecution(invocation=invocation, command=command,
                                parameters=parameters)


class TargetResolver:
    """Resolve invocation target to (device, assignment) pairs
    (the reference's CommandTargetResolver; only ASSIGNMENT targets exist
    in 2.0 — CommandTarget in sitewhere.proto)."""

    def __init__(self, registry):
        self.registry = registry

    def resolve(self, invocation: DeviceCommandInvocation
                ) -> List[Tuple[Device, DeviceAssignment]]:
        if invocation.target != CommandTarget.ASSIGNMENT:
            raise SiteWhereError(f"unsupported target {invocation.target}")
        token = invocation.target_id or invocation.device_assignment_id
        assignment = self.registry.get_device_assignment_by_token(token)
        if assignment is None:
            raise SiteWhereError(f"unknown assignment '{token}'")
        device = self.registry.get_device(assignment.device_id)
        return [(device, assignment)]


class CommandDeliveryService(LifecycleComponent):
    """Tenant-scoped command delivery engine (CommandDeliveryTenantEngine).

    Consumes inbound-enriched-command-invocations, resolves + routes +
    delivers; also the entry point for system commands (registration acks).
    """

    def __init__(self, bus: EventBus, registry, tenant: str = "default",
                 naming: Optional[TopicNaming] = None,
                 router: Optional[CommandRouter] = None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"command-delivery:{tenant}")
        self.bus = bus
        self.registry = registry
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.strategy = CommandProcessingStrategy(registry)
        self.targets = TargetResolver(registry)
        self.router = router
        self.destinations: Dict[str, CommandDestination] = {}
        m = (metrics or MetricsRegistry()).scoped("commands")
        self.delivered_meter = m.meter("delivered")
        self.undelivered_counter = m.counter("undelivered")
        self._host = ConsumerHost(
            bus, self.naming.inbound_enriched_command_invocations(tenant),
            group_id=f"command-delivery-{tenant}", handler=self._process)

    # -- wiring ------------------------------------------------------------
    def add_destination(self, destination: CommandDestination) -> None:
        self.destinations[destination.destination_id] = destination
        self.add_nested(destination)
        if self.router is None:  # first destination becomes the default route
            self.router = SingleDestinationRouter(destination.destination_id)

    def on_start(self, monitor) -> None:
        self._host.start()

    def on_stop(self, monitor) -> None:
        self._host.stop()

    # -- delivery ----------------------------------------------------------
    def _process(self, records: List[Record]) -> None:
        for record in records:
            try:
                _, event = unpack_enriched(record.value)
            except Exception as exc:
                self._park_undelivered(record, f"undecodable payload: {exc}")
                continue
            if not isinstance(event, DeviceCommandInvocation):
                continue
            try:
                self.deliver(event)
            except Exception as exc:
                self._park_undelivered(record, str(exc))

    def deliver(self, invocation: DeviceCommandInvocation) -> None:
        """Synchronous delivery path, also callable directly (tests, REST)."""
        from sitewhere_tpu.commands.encoding import calculate_nesting

        execution = self.strategy.create_execution(invocation)
        for device, assignment in self.targets.resolve(invocation):
            # composite targets deliver THROUGH their gateway
            # (DefaultCommandProcessingStrategy.java:74); routing selects
            # the destination by the GATEWAY's device type — the transport
            # that physically carries the frame
            # (DeviceTypeMappingCommandRouter routes on the gateway)
            nesting = calculate_nesting(self.registry, device)
            for destination in self._route(execution, nesting.gateway,
                                           assignment):
                destination.deliver_command(execution, device, assignment,
                                            nesting=nesting)
                self.delivered_meter.mark(1)

    def send_system_command(self, device_token: str,
                            command: SystemCommand) -> None:
        """Deliver a system message (e.g. registration ack) to one device
        (CommandRoutingLogic.routeSystemCommand)."""
        from sitewhere_tpu.commands.encoding import calculate_nesting

        device = self.registry.get_device_by_token(device_token)
        if device is None:
            raise SiteWhereError(f"unknown device '{device_token}'")
        # composite children receive system traffic (registration acks)
        # through their gateway's transport, like regular commands
        nesting = calculate_nesting(self.registry, device)
        for destination in self._route(None, nesting.gateway, None):
            destination.deliver_system_command(command, device,
                                               nesting=nesting)

    def _route(self, execution: Optional[CommandExecution], device: Device,
               assignment: Optional[DeviceAssignment]
               ) -> List[CommandDestination]:
        if self.router is None:
            raise SiteWhereError("no command destinations configured")
        return self.router.route(execution, device, assignment,
                                 self.destinations)

    def _park_undelivered(self, record: Record, reason: str) -> None:
        self.undelivered_counter.inc()
        LOGGER.warning("undelivered command invocation: %s", reason)
        self.bus.publish(
            self.naming.undelivered_command_invocations(self.tenant),
            record.key, record.value)
