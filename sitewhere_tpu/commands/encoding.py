"""Command encoders: CommandExecution -> on-the-wire bytes for a device.

Reference: service-command-delivery encoders — per-device-type protobuf via
ProtobufMessageBuilder (sitewhere-communication
protobuf/ProtobufMessageBuilder.java), Groovy scripted encoders, and
JSON encoders. Here the wire encoder emits COMMAND frames of the framework's
device wire protocol (transport/wire.py), the JSON encoder emits plain JSON
for HTTP-ish devices, and the scripted encoder takes any Python callable —
the Groovy extension point without a JVM.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Protocol

from sitewhere_tpu.model.device import Device, DeviceAssignment, DeviceCommand
from sitewhere_tpu.model.event import DeviceCommandInvocation
from sitewhere_tpu.transport.wire import MessageType, WireCodec, encode_frame


@dataclass
class CommandExecution:
    """A resolved invocation ready to encode (IDeviceCommandExecution):
    the invocation event + the command definition + coerced parameters."""

    invocation: DeviceCommandInvocation
    command: DeviceCommand
    parameters: Dict[str, str] = field(default_factory=dict)


@dataclass
class SystemCommand:
    """Cloud->device system message (non-invocation), e.g. a registration
    ack (Device.Command.ACK_REGISTRATION in sitewhere.proto)."""

    message_type: MessageType
    payload: bytes


class CommandEncoder(Protocol):
    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment]) -> bytes: ...

    def encode_system(self, command: SystemCommand, device: Device) -> bytes: ...


class WireCommandEncoder:
    """Encode as wire-protocol frames — the default binary device SDK path
    (counterpart of ProtobufExecutionEncoder)."""

    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment]) -> bytes:
        payload = WireCodec.encode_command(
            token=device.token, command=execution.command.name,
            parameters=execution.parameters,
            invocation_id=execution.invocation.id)
        return encode_frame(MessageType.COMMAND, payload)

    def encode_system(self, command: SystemCommand, device: Device) -> bytes:
        return encode_frame(command.message_type, command.payload)


class JsonCommandEncoder:
    """Encode as a JSON document (JsonCommandExecutionEncoder)."""

    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment]) -> bytes:
        return json.dumps({
            "deviceToken": device.token,
            "command": execution.command.name,
            "namespace": execution.command.namespace,
            "invocationId": execution.invocation.id,
            "parameters": execution.parameters,
        }).encode("utf-8")

    def encode_system(self, command: SystemCommand, device: Device) -> bytes:
        return json.dumps({
            "deviceToken": device.token,
            "systemCommand": MessageType(command.message_type).name,
            "payload": command.payload.hex(),
        }).encode("utf-8")


class ScriptedCommandEncoder:
    """User-supplied callable `(execution, device, assignment) -> bytes`
    (GroovyCommandExecutionEncoder's extension point)."""

    def __init__(self, script: Callable[..., bytes],
                 system_script: Optional[Callable[..., bytes]] = None):
        self.script = script
        self.system_script = system_script

    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment]) -> bytes:
        return self.script(execution, device, assignment)

    def encode_system(self, command: SystemCommand, device: Device) -> bytes:
        if self.system_script is None:
            return WireCommandEncoder().encode_system(command, device)
        return self.system_script(command, device)


def coerce_parameters(command: DeviceCommand,
                      values: Dict[str, Any]) -> Dict[str, str]:
    """Validate invocation parameter values against the command's declared
    parameters; required parameters must be present (the validation
    DefaultCommandProcessingStrategy performs before encoding)."""
    out: Dict[str, str] = {}
    declared = {p.name for p in command.parameters}
    for parameter in command.parameters:
        if parameter.name in values:
            out[parameter.name] = str(values[parameter.name])
        elif parameter.required:
            raise ValueError(
                f"missing required parameter '{parameter.name}' "
                f"for command '{command.name}'")
    for name, value in values.items():
        if name not in declared:  # pass through undeclared extras
            out[name] = str(value)
    return out
