"""Command encoders: CommandExecution -> on-the-wire bytes for a device.

Reference: service-command-delivery encoders — per-device-type protobuf via
ProtobufMessageBuilder (sitewhere-communication
protobuf/ProtobufMessageBuilder.java), Groovy scripted encoders, and
JSON encoders. Here the wire encoder emits COMMAND frames of the framework's
device wire protocol (transport/wire.py), the JSON encoder emits plain JSON
for HTTP-ish devices, and the scripted encoder takes any Python callable —
the Groovy extension point without a JVM.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Protocol

from sitewhere_tpu.model.device import Device, DeviceAssignment, DeviceCommand
from sitewhere_tpu.model.event import DeviceCommandInvocation
from sitewhere_tpu.transport.wire import MessageType, WireCodec, encode_frame


@dataclass
class CommandExecution:
    """A resolved invocation ready to encode (IDeviceCommandExecution):
    the invocation event + the command definition + coerced parameters."""

    invocation: DeviceCommandInvocation
    command: DeviceCommand
    parameters: Dict[str, str] = field(default_factory=dict)


@dataclass
class SystemCommand:
    """Cloud->device system message (non-invocation), e.g. a registration
    ack (Device.Command.ACK_REGISTRATION in sitewhere.proto)."""

    message_type: MessageType
    payload: bytes


@dataclass
class DeviceNestingContext:
    """How to address the target through its gateway
    (IDeviceNestingContext; commands/NestedDeviceSupport.java:69). For a
    standalone device the gateway IS the target and `nested` is None;
    for a composite-mapped device the transport delivers to `gateway`
    and the payload addresses `nested` at the schema `path`."""

    gateway: Device
    nested: Optional[Device] = None
    path: str = ""

    @property
    def target(self) -> Device:
        return self.nested if self.nested is not None else self.gateway


def calculate_nesting(registry, target: Device) -> DeviceNestingContext:
    """NestedDeviceSupport.calculateNestedDeviceInformation:32 — resolve
    the gateway whose transport physically carries the target's traffic;
    fall back to the target as its own gateway when unparented (or
    unmapped, which the reference treats the same way).

    Multi-level composites (A hosts B hosts C) resolve to the ROOT
    unparented ancestor — only the root has a physical connection — with
    the schema paths of every hop joined into one address
    ("busA/slotB/busB/slotC")."""
    path_segments = []
    node = target
    seen = {target.id}
    while node.parent_device_id:
        if node.parent_device_id in seen:
            break  # corrupt cycle (replication race): stop at this node
        seen.add(node.parent_device_id)
        parent = registry.devices.get(node.parent_device_id)
        if parent is None:
            # dangling backreference (parent deleted out-of-band, e.g. a
            # replicated tombstone landing before the child update):
            # deliver to the highest resolvable ancestor rather than
            # failing the command
            break
        mapping = next((m for m in parent.device_element_mappings
                        if m.device_token == node.token), None)
        if mapping is None:
            break
        path_segments.append(mapping.device_element_schema_path)
        node = parent
    if node is target:
        return DeviceNestingContext(gateway=target)
    return DeviceNestingContext(
        gateway=node, nested=target,
        path="/".join(reversed(path_segments)))


class CommandEncoder(Protocol):
    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment],
               nesting: Optional[DeviceNestingContext] = None) -> bytes: ...

    def encode_system(self, command: SystemCommand, device: Device) -> bytes: ...


class WireCommandEncoder:
    """Encode as wire-protocol frames — the default binary device SDK path
    (counterpart of ProtobufExecutionEncoder)."""

    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment],
               nesting: Optional[DeviceNestingContext] = None) -> bytes:
        parameters = dict(execution.parameters)
        if nesting is not None and nesting.nested is not None:
            # gateway-addressed frame carrying the nested target: the
            # device-side dispatcher routes on these reserved keys
            parameters["_nestedPath"] = nesting.path
            parameters["_nestedToken"] = nesting.nested.token
        payload = WireCodec.encode_command(
            token=device.token, command=execution.command.name,
            parameters=parameters,
            invocation_id=execution.invocation.id)
        return encode_frame(MessageType.COMMAND, payload)

    def encode_system(self, command: SystemCommand, device: Device) -> bytes:
        return encode_frame(command.message_type, command.payload)


class JsonCommandEncoder:
    """Encode as a JSON document (JsonCommandExecutionEncoder)."""

    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment],
               nesting: Optional[DeviceNestingContext] = None) -> bytes:
        doc = {
            "deviceToken": device.token,
            "command": execution.command.name,
            "namespace": execution.command.namespace,
            "invocationId": execution.invocation.id,
            "parameters": execution.parameters,
        }
        if nesting is not None and nesting.nested is not None:
            doc["nesting"] = {"gateway": nesting.gateway.token,
                              "nested": nesting.nested.token,
                              "path": nesting.path}
        return json.dumps(doc).encode("utf-8")

    def encode_system(self, command: SystemCommand, device: Device) -> bytes:
        return json.dumps({
            "deviceToken": device.token,
            "systemCommand": MessageType(command.message_type).name,
            "payload": command.payload.hex(),
        }).encode("utf-8")


class ScriptedCommandEncoder:
    """User-supplied callable `(execution, device, assignment) -> bytes`
    (GroovyCommandExecutionEncoder's extension point). Scripts that
    declare a `nesting` keyword receive the composite-delivery context;
    legacy three-argument scripts keep working."""

    def __init__(self, script: Callable[..., bytes],
                 system_script: Optional[Callable[..., bytes]] = None):
        self.script = script
        self.system_script = system_script
        import inspect
        try:
            self._script_accepts_nesting = "nesting" in \
                inspect.signature(script).parameters
        except (TypeError, ValueError):
            self._script_accepts_nesting = False

    def encode(self, execution: CommandExecution, device: Device,
               assignment: Optional[DeviceAssignment],
               nesting: Optional[DeviceNestingContext] = None) -> bytes:
        if self._script_accepts_nesting:
            return self.script(execution, device, assignment,
                               nesting=nesting)
        return self.script(execution, device, assignment)

    def encode_system(self, command: SystemCommand, device: Device) -> bytes:
        if self.system_script is None:
            return WireCommandEncoder().encode_system(command, device)
        return self.system_script(command, device)


def coerce_parameters(command: DeviceCommand,
                      values: Dict[str, Any]) -> Dict[str, str]:
    """Validate invocation parameter values against the command's declared
    parameters; required parameters must be present (the validation
    DefaultCommandProcessingStrategy performs before encoding)."""
    out: Dict[str, str] = {}
    declared = {p.name for p in command.parameters}
    for parameter in command.parameters:
        if parameter.name in values:
            out[parameter.name] = str(values[parameter.name])
        elif parameter.required:
            raise ValueError(
                f"missing required parameter '{parameter.name}' "
                f"for command '{command.name}'")
    for name, value in values.items():
        if name not in declared:  # pass through undeclared extras
            out[name] = str(value)
    return out
