"""Outbound command routers: pick the destination for an execution.

Reference: service-command-delivery routing/ — IOutboundCommandRouter with
DeviceTypeMappingCommandRouter (map device-type token -> destination id with
a fallback) and the single-destination NoOpCommandRouter.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Protocol

from sitewhere_tpu.commands.destinations import CommandDestination
from sitewhere_tpu.commands.encoding import CommandExecution
from sitewhere_tpu.errors import SiteWhereError
from sitewhere_tpu.model.device import Device, DeviceAssignment


class CommandRouter(Protocol):
    def route(self, execution: Optional[CommandExecution], device: Device,
              assignment: Optional[DeviceAssignment],
              destinations: Dict[str, CommandDestination]
              ) -> List[CommandDestination]: ...


class SingleDestinationRouter:
    """Route everything to one destination (the implicit default when a
    tenant configures exactly one destination)."""

    def __init__(self, destination_id: str):
        self.destination_id = destination_id

    def route(self, execution, device, assignment, destinations):
        if self.destination_id not in destinations:
            raise SiteWhereError(
                f"unknown command destination '{self.destination_id}'")
        return [destinations[self.destination_id]]


class DeviceTypeMappingRouter:
    """Map device-type token -> destination id, with optional default
    (DeviceTypeMappingCommandRouter.java). Needs the registry to resolve the
    device's type token from its id."""

    def __init__(self, registry, mappings: Dict[str, str],
                 default_destination: Optional[str] = None):
        self.registry = registry
        self.mappings = dict(mappings)
        self.default_destination = default_destination

    def route(self, execution, device, assignment, destinations):
        device_type = self.registry.get_device_type(device.device_type_id)
        destination_id = self.mappings.get(
            device_type.token if device_type else "",
            self.default_destination)
        if destination_id is None:
            raise SiteWhereError(
                f"no destination mapping for device type of '{device.token}'")
        if destination_id not in destinations:
            raise SiteWhereError(
                f"unknown command destination '{destination_id}'")
        return [destinations[destination_id]]


class BroadcastRouter:
    """Deliver to every destination — useful for redundant transports."""

    def route(self, execution, device, assignment, destinations):
        return list(destinations.values())
