"""Cloud->device command delivery (reference: service-command-delivery)."""

from sitewhere_tpu.commands.delivery import (
    CommandDeliveryService, CommandProcessingStrategy, TargetResolver)
from sitewhere_tpu.commands.destinations import (
    CoapDeliveryProvider, CommandDestination, InProcDeliveryProvider,
    MetadataParameterExtractor, MqttDeliveryProvider, MqttParameterExtractor,
    SmsDeliveryProvider, SmsParameterExtractor)
from sitewhere_tpu.commands.encoding import (
    CommandExecution, JsonCommandEncoder, ScriptedCommandEncoder,
    SystemCommand, WireCommandEncoder, coerce_parameters)
from sitewhere_tpu.commands.routing import (
    BroadcastRouter, DeviceTypeMappingRouter, SingleDestinationRouter)

__all__ = [
    "BroadcastRouter", "CoapDeliveryProvider", "CommandDeliveryService",
    "CommandDestination", "CommandExecution", "CommandProcessingStrategy",
    "DeviceTypeMappingRouter", "InProcDeliveryProvider", "JsonCommandEncoder",
    "MetadataParameterExtractor", "MqttDeliveryProvider",
    "MqttParameterExtractor", "ScriptedCommandEncoder",
    "SingleDestinationRouter", "SmsDeliveryProvider",
    "SmsParameterExtractor", "SystemCommand", "TargetResolver",
    "WireCommandEncoder", "coerce_parameters",
]
