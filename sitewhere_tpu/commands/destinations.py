"""Command destinations: encoder + parameter extractor + delivery provider.

Reference: service-command-delivery destination/ — a CommandDestination
combines an ICommandExecutionEncoder, an ICommandDeliveryParameterExtractor
(e.g. MqttParameterExtractor building per-device topic names) and an
ICommandDeliveryProvider (MqttCommandDeliveryProvider.java, CoAP, SMS).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from sitewhere_tpu.commands.encoding import (
    CommandEncoder, CommandExecution, SystemCommand, WireCommandEncoder)
from sitewhere_tpu.model.device import Device, DeviceAssignment
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.sources.receivers import EventLoopThread
from sitewhere_tpu.transport.coap import CoapClient
from sitewhere_tpu.transport.mqtt import MqttClient

LOGGER = logging.getLogger("sitewhere.commands")


class ParameterExtractor(Protocol):
    """Compute per-delivery routing parameters (topic/path/phone number)."""

    def extract(self, device: Device,
                assignment: Optional[DeviceAssignment]) -> Dict[str, str]: ...


class MqttParameterExtractor:
    """Default topic scheme: commands on SW/{device}/command, system
    messages on SW/{device}/system (DefaultMqttParameterExtractor's
    {command,system}Topic expressions)."""

    def __init__(self, command_topic: str = "SW/{token}/command",
                 system_topic: str = "SW/{token}/system"):
        self.command_topic = command_topic
        self.system_topic = system_topic

    def extract(self, device: Device,
                assignment: Optional[DeviceAssignment]) -> Dict[str, str]:
        return {
            "commandTopic": self.command_topic.format(token=device.token),
            "systemTopic": self.system_topic.format(token=device.token),
        }


class MetadataParameterExtractor:
    """Read routing parameters straight from device metadata (the pattern
    CoapMetadataParameterExtractor uses for per-device host/port)."""

    def __init__(self, keys: Dict[str, str],
                 defaults: Optional[Dict[str, str]] = None):
        self.keys = keys  # param name -> metadata key
        self.defaults = defaults or {}

    def extract(self, device: Device,
                assignment: Optional[DeviceAssignment]) -> Dict[str, str]:
        out = dict(self.defaults)
        for name, meta_key in self.keys.items():
            if meta_key in device.metadata:
                out[name] = device.metadata[meta_key]
        return out


class DeliveryProvider(Protocol):
    def deliver(self, device: Device, encoded: bytes,
                parameters: Dict[str, str]) -> None: ...

    def deliver_system(self, device: Device, encoded: bytes,
                       parameters: Dict[str, str]) -> None: ...


class MqttDeliveryProvider(LifecycleComponent):
    """Publish encoded commands to the device's MQTT topics
    (MqttCommandDeliveryProvider.java)."""

    def __init__(self, host: str, port: int,
                 client_id: Optional[str] = None,
                 loop_thread: Optional[EventLoopThread] = None):
        super().__init__("mqtt-delivery")
        self.host = host
        self.port = port
        # unique default: two providers on one broker must not take over
        # each other's MQTT session
        from sitewhere_tpu.model.common import new_id
        self.client_id = client_id or f"command-delivery-{new_id()[:8]}"
        self._loop_thread = loop_thread
        self._client: Optional[MqttClient] = None

    @property
    def loop_thread(self) -> EventLoopThread:
        if self._loop_thread is None:
            self._loop_thread = EventLoopThread.shared()
        return self._loop_thread

    def on_start(self, monitor) -> None:
        client = MqttClient(self.host, self.port, client_id=self.client_id)
        self.loop_thread.run(client.connect())
        self._client = client

    def on_stop(self, monitor) -> None:
        if self._client is not None:
            self.loop_thread.run(self._client.disconnect())
            self._client = None

    def _publish(self, topic: str, payload: bytes) -> None:
        if self._client is None:
            raise RuntimeError("mqtt delivery provider not started")
        self.loop_thread.run(self._client.publish(topic, payload))

    def deliver(self, device: Device, encoded: bytes,
                parameters: Dict[str, str]) -> None:
        self._publish(parameters["commandTopic"], encoded)

    def deliver_system(self, device: Device, encoded: bytes,
                       parameters: Dict[str, str]) -> None:
        self._publish(parameters["systemTopic"], encoded)


class CoapDeliveryProvider(LifecycleComponent):
    """POST encoded commands to the device's CoAP endpoint; host/port/paths
    come from extractor parameters (CoapCommandDeliveryProvider.java)."""

    def __init__(self, loop_thread: Optional[EventLoopThread] = None,
                 confirmable: bool = True):
        super().__init__("coap-delivery")
        self._loop_thread = loop_thread
        self.confirmable = confirmable

    @property
    def loop_thread(self) -> EventLoopThread:
        if self._loop_thread is None:
            self._loop_thread = EventLoopThread.shared()
        return self._loop_thread

    def _post(self, parameters: Dict[str, str], path: str,
              payload: bytes) -> None:
        client = CoapClient(parameters["host"], int(parameters["port"]))
        self.loop_thread.run(
            client.post(path, payload, confirmable=self.confirmable))

    def deliver(self, device: Device, encoded: bytes,
                parameters: Dict[str, str]) -> None:
        self._post(parameters, parameters.get("commandPath", "command"),
                   encoded)

    def deliver_system(self, device: Device, encoded: bytes,
                       parameters: Dict[str, str]) -> None:
        self._post(parameters, parameters.get("systemPath", "system"),
                   encoded)


class SmsParameterExtractor:
    """Phone number from device metadata (the reference's
    SmsParameterExtractor resolves per-device SMS routing the same way)."""

    def __init__(self, phone_metadata_key: str = "sms.phone"):
        self.phone_metadata_key = phone_metadata_key

    def extract(self, device: Device,
                assignment: Optional[DeviceAssignment]) -> Dict[str, str]:
        phone = device.metadata.get(self.phone_metadata_key, "")
        return {"phone": phone}


class SmsDeliveryProvider(LifecycleComponent):
    """Deliver encoded commands as SMS messages
    (destination/sms/SmsCommandDestination.java + Twilio provider).

    Gated like the broker adapters: the Twilio client library is optional
    in this image, so constructing with no `send_fn` requires it at start
    (require_optional -> clear 501). A custom `send_fn(to, from_, body)`
    plugs in any SMS gateway (and makes the provider testable in-proc).
    Binary payloads ride base64; textual payloads go through as-is."""

    def __init__(self, account_sid: str = "", auth_token: str = "",
                 from_number: str = "",
                 send_fn: Optional[Callable[[str, str, str], None]] = None):
        super().__init__("sms-delivery")
        self.account_sid = account_sid
        self.auth_token = auth_token
        self.from_number = from_number
        self._send_fn = send_fn

    def on_start(self, monitor) -> None:
        if self._send_fn is None:
            from sitewhere_tpu.sources.receivers_ext import require_optional
            twilio_rest = require_optional("twilio.rest", "Twilio SMS")
            client = twilio_rest.Client(self.account_sid, self.auth_token)

            def send(to: str, from_: str, body: str) -> None:
                client.messages.create(to=to, from_=from_, body=body)

            self._send_fn = send

    @staticmethod
    def _as_text(encoded: bytes) -> str:
        # Always prefixed ("txt:" / "b64:"): an unprefixed scheme would be
        # ambiguous — a binary frame that happens to decode as UTF-8 would
        # arrive looking like text, and the device couldn't tell which
        # decoding to apply.
        try:
            return "txt:" + encoded.decode("utf-8")
        except UnicodeDecodeError:
            import base64
            return "b64:" + base64.b64encode(encoded).decode("ascii")

    def _send(self, device: Device, encoded: bytes,
              parameters: Dict[str, str]) -> None:
        if self._send_fn is None:
            raise RuntimeError("sms delivery provider not started")
        phone = parameters.get("phone", "")
        if not phone:
            from sitewhere_tpu.errors import SiteWhereError
            raise SiteWhereError(
                f"device {device.token} has no SMS phone number metadata")
        self._send_fn(phone, self.from_number, self._as_text(encoded))

    def deliver(self, device: Device, encoded: bytes,
                parameters: Dict[str, str]) -> None:
        self._send(device, encoded, parameters)

    def deliver_system(self, device: Device, encoded: bytes,
                       parameters: Dict[str, str]) -> None:
        self._send(device, encoded, parameters)


class InProcDeliveryProvider(LifecycleComponent):
    """Hand deliveries to a Python callback — used by tests and by co-located
    device simulators (no reference equivalent needed: the in-proc path)."""

    def __init__(self, callback: Optional[Callable[..., None]] = None):
        super().__init__("inproc-delivery")
        self.callback = callback
        self.delivered: List[Tuple[str, bytes, Dict[str, str]]] = []
        self.system: List[Tuple[str, bytes, Dict[str, str]]] = []

    def deliver(self, device: Device, encoded: bytes,
                parameters: Dict[str, str]) -> None:
        self.delivered.append((device.token, encoded, parameters))
        if self.callback:
            self.callback("command", device, encoded, parameters)

    def deliver_system(self, device: Device, encoded: bytes,
                       parameters: Dict[str, str]) -> None:
        self.system.append((device.token, encoded, parameters))
        if self.callback:
            self.callback("system", device, encoded, parameters)


class CommandDestination(LifecycleComponent):
    """One fully-wired delivery path (ICommandDestination): encoder +
    parameter extractor + delivery provider, addressed by id from routers."""

    def __init__(self, destination_id: str,
                 provider: DeliveryProvider,
                 encoder: Optional[CommandEncoder] = None,
                 extractor: Optional[ParameterExtractor] = None):
        super().__init__(f"command-destination:{destination_id}")
        self.destination_id = destination_id
        self.encoder = encoder or WireCommandEncoder()
        self.extractor = extractor or MqttParameterExtractor()
        self.provider = provider
        self._encoder_accepts_nesting: Optional[bool] = None
        if isinstance(provider, LifecycleComponent):
            self.add_nested(provider)

    def deliver_command(self, execution: CommandExecution, device: Device,
                        assignment: Optional[DeviceAssignment],
                        nesting=None) -> None:
        """Encode + extract + deliver. With a nesting context the
        TRANSPORT addresses the gateway (its MQTT topic / CoAP endpoint /
        phone number) while the payload addresses the nested target —
        CommandDestination.deliverCommand:60 passing nesting to both the
        encoder and the parameter extractor."""
        encoded = self._encode(execution, device, assignment, nesting)
        transport_device = (nesting.gateway if nesting is not None
                            else device)
        parameters = self.extractor.extract(transport_device, assignment)
        self.provider.deliver(transport_device, encoded, parameters)

    def _encode(self, execution, device, assignment, nesting) -> bytes:
        if nesting is None:
            return self.encoder.encode(execution, device, assignment)
        accepts = self._encoder_accepts_nesting
        if accepts is None:
            # resolved once per destination: third-party encoders may
            # predate the nesting-aware CommandEncoder protocol
            import inspect
            try:
                accepts = "nesting" in inspect.signature(
                    self.encoder.encode).parameters
            except (TypeError, ValueError):
                accepts = False
            self._encoder_accepts_nesting = accepts
        if accepts:
            return self.encoder.encode(execution, device, assignment,
                                       nesting=nesting)
        # encoder predates the nesting-aware protocol: deliver without
        # payload-level nesting (gateway addressing still applies)
        return self.encoder.encode(execution, device, assignment)

    def deliver_system_command(self, command: SystemCommand,
                               device: Device, nesting=None) -> None:
        """System payloads always name the TARGET device; with a nesting
        context the transport (topic/endpoint/phone) addresses the
        gateway that physically carries it — same split as
        deliver_command."""
        encoded = self.encoder.encode_system(command, device)
        transport_device = (nesting.gateway if nesting is not None
                            else device)
        parameters = self.extractor.extract(transport_device, None)
        self.provider.deliver_system(transport_device, encoded, parameters)
