"""REST client (reference: sitewhere-client — ISiteWhereClient /
rest/client/SiteWhereClient.java:91)."""

from sitewhere_tpu.client.rest import SiteWhereClient, SiteWhereClientError

__all__ = ["SiteWhereClient", "SiteWhereClientError"]
