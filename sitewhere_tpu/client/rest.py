"""Python REST client for the sitewhere_tpu gateway.

Reference: sitewhere-client/src/main/java/com/sitewhere/rest/client/
SiteWhereClient.java:91 (ISiteWhereClient surface: authenticate, device/
assignment/event CRUD against the REST gateway). Dependency-free: stdlib
urllib with JWT bearer auth and the X-SiteWhere-Tenant header.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional


class SiteWhereClientError(Exception):
    def __init__(self, status: int, payload: Any):
        super().__init__(f"HTTP {status}: {payload}")
        self.status = status
        self.payload = payload


class SiteWhereClient:
    """Authenticated client bound to one instance + tenant."""

    def __init__(self, base_url: str, tenant: str = "default",
                 timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.tenant = tenant
        self.timeout = timeout
        self.token: Optional[str] = None

    # -- transport ---------------------------------------------------------
    def _request(self, method: str, path: str, body: Any = None,
                 params: Optional[Dict[str, Any]] = None,
                 headers: Optional[Dict[str, str]] = None) -> Any:
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None})
        data = None
        req_headers = {"Accept": "application/json"}
        if isinstance(body, bytes):
            data = body
            req_headers["Content-Type"] = "application/octet-stream"
        elif body is not None:
            data = json.dumps(body).encode("utf-8")
            req_headers["Content-Type"] = "application/json"
        if self.token:
            req_headers["Authorization"] = f"Bearer {self.token}"
        if self.tenant:
            req_headers["X-SiteWhere-Tenant"] = self.tenant
        if headers:
            req_headers.update(headers)
        request = urllib.request.Request(url, data=data, method=method,
                                         headers=req_headers)
        try:
            with urllib.request.urlopen(request,
                                        timeout=self.timeout) as resp:
                raw = resp.read()
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as err:
            raw = err.read()
            try:
                payload = json.loads(raw)
            except Exception:
                payload = raw.decode("utf-8", "replace")
            raise SiteWhereClientError(err.code, payload)
        if "json" in ctype:
            return json.loads(raw) if raw else None
        return raw  # binary endpoints: empty body is b"", not None

    def get(self, path: str, **params) -> Any:
        return self._request("GET", path, params=params or None)

    def post(self, path: str, body: Any = None) -> Any:
        return self._request("POST", path, body=body)

    def put(self, path: str, body: Any = None) -> Any:
        return self._request("PUT", path, body=body)

    def delete(self, path: str) -> Any:
        return self._request("DELETE", path)

    # -- auth --------------------------------------------------------------
    def authenticate(self, username: str, password: str) -> str:
        creds = base64.b64encode(f"{username}:{password}".encode()).decode()
        result = self._request("POST", "/authapi/jwt",
                               headers={"Authorization": f"Basic {creds}"})
        self.token = result["token"]
        return self.token

    # -- system ------------------------------------------------------------
    def get_version(self) -> Dict:
        return self.get("/api/system/version")

    def get_topology(self) -> Dict:
        return self.get("/api/instance/topology")

    # -- tenants -----------------------------------------------------------
    def create_tenant(self, body: Dict) -> Dict:
        return self.post("/api/tenants", body)

    def list_tenants(self) -> Dict:
        return self.get("/api/tenants")

    def get_tenant(self, token: str) -> Dict:
        return self.get(f"/api/tenants/{token}")

    # -- users -------------------------------------------------------------
    def create_user(self, body: Dict) -> Dict:
        return self.post("/api/users", body)

    def list_users(self) -> Dict:
        return self.get("/api/users")

    # -- device types ------------------------------------------------------
    def create_device_type(self, body: Dict) -> Dict:
        return self.post("/api/devicetypes", body)

    def get_device_type(self, token: str) -> Dict:
        return self.get(f"/api/devicetypes/{token}")

    def list_device_types(self) -> Dict:
        return self.get("/api/devicetypes")

    def create_device_command(self, device_type_token: str,
                              body: Dict) -> Dict:
        return self.post(f"/api/devicetypes/{device_type_token}/commands",
                         body)

    # -- devices -----------------------------------------------------------
    def create_device(self, body: Dict) -> Dict:
        return self.post("/api/devices", body)

    def get_device(self, token: str) -> Dict:
        return self.get(f"/api/devices/{token}")

    def list_devices(self, **params) -> Dict:
        return self.get("/api/devices", **params)

    def delete_device(self, token: str) -> Dict:
        return self.delete(f"/api/devices/{token}")

    def add_device_event_batch(self, device_token: str, batch: Dict) -> Dict:
        return self.post(f"/api/devices/{device_token}/events", batch)

    # -- labels (reference: sitewhere-client label endpoints) --------------
    def list_label_generators(self) -> Dict:
        return self.get("/api/labels/generators")

    def get_label(self, entity_path: str, token: str,
                  generator_id: str = "qrcode") -> bytes:
        """PNG label for an entity; entity_path is the REST collection name
        (devices, devicetypes, assignments, areas, customers, assets)."""
        return self.get(f"/api/{entity_path}/{token}/label/{generator_id}")

    def get_device_label(self, token: str,
                         generator_id: str = "qrcode") -> bytes:
        return self.get_label("devices", token, generator_id)

    def list_device_events(self, device_token: str, **params) -> Dict:
        return self.get(f"/api/devices/{device_token}/events", **params)

    # -- assignments -------------------------------------------------------
    def create_assignment(self, body: Dict) -> Dict:
        return self.post("/api/assignments", body)

    def get_assignment(self, token: str) -> Dict:
        return self.get(f"/api/assignments/{token}")

    def release_assignment(self, token: str) -> Dict:
        return self.post(f"/api/assignments/{token}/end")

    def add_measurements(self, assignment_token: str, *events: Dict) -> Any:
        return self.post(f"/api/assignments/{assignment_token}/measurements",
                         list(events))

    def add_locations(self, assignment_token: str, *events: Dict) -> Any:
        return self.post(f"/api/assignments/{assignment_token}/locations",
                         list(events))

    def add_alerts(self, assignment_token: str, *events: Dict) -> Any:
        return self.post(f"/api/assignments/{assignment_token}/alerts",
                         list(events))

    def list_measurements(self, assignment_token: str, **params) -> Dict:
        return self.get(f"/api/assignments/{assignment_token}/measurements",
                        **params)

    def list_locations(self, assignment_token: str, **params) -> Dict:
        return self.get(f"/api/assignments/{assignment_token}/locations",
                        **params)

    def list_alerts(self, assignment_token: str, **params) -> Dict:
        return self.get(f"/api/assignments/{assignment_token}/alerts",
                        **params)

    def invoke_command(self, assignment_token: str, body: Dict) -> Dict:
        return self.post(f"/api/assignments/{assignment_token}/invocations",
                         body)

    # -- areas / zones -----------------------------------------------------
    def create_area(self, body: Dict) -> Dict:
        return self.post("/api/areas", body)

    def create_zone(self, area_token: str, body: Dict) -> Dict:
        return self.post(f"/api/areas/{area_token}/zones", body)

    # -- assets ------------------------------------------------------------
    def create_asset_type(self, body: Dict) -> Dict:
        return self.post("/api/assettypes", body)

    def create_asset(self, body: Dict) -> Dict:
        return self.post("/api/assets", body)

    # -- batch / schedules -------------------------------------------------
    def create_batch_command_invocation(self, body: Dict) -> Dict:
        return self.post("/api/batch/command", body)

    def get_batch_operation(self, token: str) -> Dict:
        return self.get(f"/api/batch/{token}")

    def create_schedule(self, body: Dict) -> Dict:
        return self.post("/api/schedules", body)

    def create_scheduled_job(self, body: Dict) -> Dict:
        return self.post("/api/jobs", body)

    # -- device streams ----------------------------------------------------
    def create_device_stream(self, assignment_token: str, stream_id: str,
                             content_type: str = "application/octet-stream"
                             ) -> Dict:
        return self.post(f"/api/assignments/{assignment_token}/streams",
                         {"stream_id": stream_id,
                          "content_type": content_type})

    def add_stream_data(self, assignment_token: str, stream_id: str,
                        sequence: int, data: bytes) -> Dict:
        return self._request(
            "POST", f"/api/assignments/{assignment_token}/streams/"
                    f"{stream_id}/data/{sequence}", body=data)

    def get_stream_data(self, assignment_token: str, stream_id: str,
                        sequence: int) -> bytes:
        return self.get(f"/api/assignments/{assignment_token}/streams/"
                        f"{stream_id}/data/{sequence}")

    def get_stream_content(self, assignment_token: str,
                           stream_id: str) -> bytes:
        return self.get(f"/api/assignments/{assignment_token}/streams/"
                        f"{stream_id}/content")

    # -- event search ------------------------------------------------------
    def search_events(self, provider_id: str = "columnar", **params) -> Dict:
        return self.get(f"/api/search/{provider_id}/events", **params)

    # -- device state ------------------------------------------------------
    def get_device_state(self, device_token: str) -> Dict:
        return self.get(f"/api/devicestates/{device_token}")
