"""Batch + streaming analytics over the event plane (sitewhere-spark
replacement): windowed segment-reduction kernels, replay engines, and a
micro-batch stream receiver."""

from sitewhere_tpu.analytics.engine import (
    BusReplayAnalytics, WindowReport, WindowedAnalyticsEngine)
from sitewhere_tpu.analytics.receiver import EventStreamReceiver, MicroBatch
from sitewhere_tpu.analytics.windows import (
    WindowedStats, compact_keys, event_type_histogram, windowed_stats)

__all__ = [
    "BusReplayAnalytics", "EventStreamReceiver", "MicroBatch",
    "WindowReport", "WindowedAnalyticsEngine", "WindowedStats",
    "compact_keys", "event_type_histogram", "windowed_stats",
]
