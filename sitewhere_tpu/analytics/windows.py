"""Windowed tensor reductions: the batch-analytics kernel layer.

Reference: the reference's only analytics bridge is `sitewhere-spark`
(SiteWhereReceiver.java:31) — it ships events to Spark Streaming and lets
Spark do windowed aggregation off-platform. Here the analytics run ON the
accelerator as one segment-reduction pass: events keyed by
(key, time-bucket) fold into dense [K, W] stat grids (count/sum/mean/min/
max) in a single XLA program — no external cluster.

Design (TPU-first): a (key, window) pair maps to one segment id
`key * n_windows + bucket`; out-of-range or invalid rows map to a dropped
trailing segment. All five statistics come from three `segment_*` calls over
static shapes, so one compiled program serves any replay size at a given
(K, W) bucket shape. int64-safe: absolute ms timestamps are rebased to the
window origin on the host before entering the kernel.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class WindowedStats:
    """Dense per-(key, window) statistics, all shape [K, W].

    `mean`/`min`/`max` are NaN where count == 0 (query layers mask on count).
    """

    count: jnp.ndarray  # int32
    sum: jnp.ndarray    # float32
    mean: jnp.ndarray   # float32
    min: jnp.ndarray    # float32
    max: jnp.ndarray    # float32

    @property
    def num_keys(self) -> int:
        return self.count.shape[0]

    @property
    def num_windows(self) -> int:
        return self.count.shape[1]


def _windowed_stats_impl(keys: jnp.ndarray, ts_rel: jnp.ndarray,
                         value: jnp.ndarray, valid: jnp.ndarray,
                         window_ms: jnp.ndarray,
                         num_keys: int, n_windows: int) -> WindowedStats:
    bucket = (ts_rel // window_ms).astype(jnp.int32)
    in_range = valid & (bucket >= 0) & (bucket < n_windows) & \
        (keys >= 0) & (keys < num_keys)
    S = num_keys * n_windows
    seg = jnp.where(in_range, keys * n_windows + bucket, S)

    ones = in_range.astype(jnp.int32)
    count = jax.ops.segment_sum(ones, seg, num_segments=S + 1)
    vsum = jax.ops.segment_sum(jnp.where(in_range, value, 0.0), seg,
                               num_segments=S + 1)
    vmin = jax.ops.segment_min(jnp.where(in_range, value, jnp.inf), seg,
                               num_segments=S + 1)
    vmax = jax.ops.segment_max(jnp.where(in_range, value, -jnp.inf), seg,
                               num_segments=S + 1)
    count = count[:S].reshape(num_keys, n_windows)
    vsum = vsum[:S].reshape(num_keys, n_windows)
    vmin = vmin[:S].reshape(num_keys, n_windows)
    vmax = vmax[:S].reshape(num_keys, n_windows)
    empty = count == 0
    nan = jnp.float32(jnp.nan)
    return WindowedStats(
        count=count.astype(jnp.int32),
        sum=vsum.astype(jnp.float32),
        mean=jnp.where(empty, nan, vsum / jnp.maximum(count, 1)).astype(
            jnp.float32),
        min=jnp.where(empty, nan, vmin).astype(jnp.float32),
        max=jnp.where(empty, nan, vmax).astype(jnp.float32))


@lru_cache(maxsize=64)
def _compiled_stats(num_keys: int, n_windows: int):
    return jax.jit(lambda k, t, v, m, w: _windowed_stats_impl(
        k, t, v, m, w, num_keys, n_windows))


def windowed_stats(keys, ts_rel, value, valid, *, window_ms: int,
                   num_keys: int, n_windows: int) -> WindowedStats:
    """count/sum/mean/min/max of `value` per (key, time-bucket).

    Args:
      keys:    int32 [B] dense key indices in [0, num_keys)
      ts_rel:  int  [B] ms relative to the window origin (host-rebased)
      value:   f32  [B]
      valid:   bool [B]
      window_ms: bucket width (dynamic — does not trigger recompiles)
      num_keys / n_windows: static grid shape (compiled per shape, cached)
    """
    fn = _compiled_stats(int(num_keys), int(n_windows))
    return fn(jnp.asarray(keys, jnp.int32), jnp.asarray(ts_rel, jnp.int32),
              jnp.asarray(value, jnp.float32), jnp.asarray(valid, bool),
              jnp.asarray(window_ms, jnp.int32))


def _type_histogram_impl(event_type: jnp.ndarray, ts_rel: jnp.ndarray,
                         valid: jnp.ndarray, window_ms: jnp.ndarray,
                         n_types: int, n_windows: int) -> jnp.ndarray:
    bucket = (ts_rel // window_ms).astype(jnp.int32)
    in_range = valid & (bucket >= 0) & (bucket < n_windows) & \
        (event_type >= 0) & (event_type < n_types)
    S = n_types * n_windows
    seg = jnp.where(in_range, event_type * n_windows + bucket, S)
    counts = jax.ops.segment_sum(in_range.astype(jnp.int32), seg,
                                 num_segments=S + 1)
    return counts[:S].reshape(n_types, n_windows)


@lru_cache(maxsize=32)
def _compiled_histogram(n_types: int, n_windows: int):
    return jax.jit(lambda e, t, m, w: _type_histogram_impl(
        e, t, m, w, n_types, n_windows))


def event_type_histogram(event_type, ts_rel, valid, *, window_ms: int,
                         n_types: int, n_windows: int) -> jnp.ndarray:
    """Event counts per (event-type, time-bucket) -> int32 [n_types, W]."""
    fn = _compiled_histogram(int(n_types), int(n_windows))
    return fn(jnp.asarray(event_type, jnp.int32),
              jnp.asarray(ts_rel, jnp.int32), jnp.asarray(valid, bool),
              jnp.asarray(window_ms, jnp.int32))


def dense_key_span(sel: np.ndarray) -> Optional[Tuple[int, int]]:
    """(lo, span) when the presence-table regime applies to these keys:
    integer dtype, and a range either genuinely dense (span <= 4n) or
    bounded by registry capacity with enough rows to amortize the
    span-sized tables. One shared decision for every caller that switches
    between scatter-table and sort-based key handling — the regimes must
    flip together."""
    if sel.size == 0 or not np.issubdtype(sel.dtype, np.integer):
        return None
    lo = int(sel.min())
    span = int(sel.max()) - lo + 1
    n = int(sel.size)
    if span <= 4 * n or (n >= 4096 and span <= (1 << 22)):
        return lo, span
    return None


def compact_keys(raw: np.ndarray,
                 valid: Optional[np.ndarray] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side key compaction: sparse ids -> dense [0, U) indices.

    Device ids span the full registry capacity; a replay usually touches a
    small subset. Compaction keeps the [K, W] stat grid proportional to the
    keys actually present. Returns (dense_keys, unique_raw_ids); rows not in
    `valid` get key -1 (dropped by the kernel's range check).
    """
    raw = np.asarray(raw)
    if valid is None:
        valid = np.ones(len(raw), bool)
    sel = raw[valid]
    if sel.size == 0:
        return np.full(len(raw), -1, np.int32), sel[:0]
    regime = dense_key_span(sel)
    if regime is not None:
        # Bounded integer key range (device indices are registry-capacity-
        # bounded): presence table + remap gather is O(n + span) and
        # replaces the sort-based unique + searchsorted, which dominated
        # replay cost (~130 ms of a 260 ms replay at 650k rows).
        lo, span = regime
        present = np.zeros(span, bool)
        present[sel - lo] = True
        uniq_off = np.nonzero(present)[0]
        remap = np.full(span, -1, np.int32)
        remap[uniq_off] = np.arange(len(uniq_off), dtype=np.int32)
        in_range = valid & (raw >= lo) & (raw <= lo + span - 1)
        shifted = np.clip(raw - lo, 0, span - 1)
        dense = np.where(in_range, remap[shifted], -1).astype(np.int32)
        return dense, (uniq_off + lo).astype(raw.dtype)
    # sparse fallback: non-integer keys, tiny row counts, or keys
    # scattered over a huge range
    uniq = np.unique(sel)
    dense = np.searchsorted(uniq, raw).astype(np.int32)
    # searchsorted gives arbitrary in-range slots for absent values; mask them
    dense = np.where(valid & (uniq[np.clip(dense, 0, len(uniq) - 1)] == raw),
                     dense, -1).astype(np.int32)
    return dense, uniq
