"""Streaming micro-batch receiver: the sitewhere-spark bridge, in-proc.

Reference: sitewhere-spark/SiteWhereReceiver.java:31 — a Spark Streaming
`Receiver<IDeviceEvent>` subscribing to Hazelcast event topics and calling
`store(event)` per message so Spark can window them. Here the receiver is a
lifecycle component consuming `inbound-enriched-events` with its own group
(so it never steals records from connectors/command delivery), decoding the
enriched envelope, and handing micro-batches of (context, event) pairs to a
user callback — the integration point for external stream processors.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from sitewhere_tpu.model.event import DeviceEvent, DeviceEventContext
from sitewhere_tpu.pipeline.enrichment import unpack_enriched
from sitewhere_tpu.runtime.bus import ConsumerHost, EventBus, Record, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

MicroBatch = List[Tuple[DeviceEventContext, DeviceEvent]]


class EventStreamReceiver(LifecycleComponent):
    """Delivers enriched events to `handler` in micro-batches."""

    def __init__(self, bus: EventBus, tenant: str,
                 handler: Callable[[MicroBatch], None],
                 naming: Optional[TopicNaming] = None,
                 group_id: Optional[str] = None, max_batch: int = 4096,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"stream-receiver:{tenant}")
        self.tenant = tenant
        self.handler = handler
        naming = naming or TopicNaming()
        m = (metrics or MetricsRegistry()).scoped("stream_receiver")
        self.received_meter = m.meter("received")
        self.failed_counter = m.counter("decode_failed")
        self._host = ConsumerHost(
            bus, naming.inbound_enriched_events(tenant),
            group_id=group_id or f"stream-receiver-{tenant}",
            handler=self._process, max_records=max_batch)

    def on_start(self, monitor) -> None:
        self._host.start()

    def on_stop(self, monitor) -> None:
        self._host.stop()

    def _process(self, records: List[Record]) -> None:
        batch: MicroBatch = []
        for record in records:
            try:
                batch.append(unpack_enriched(record.value))
            except Exception:
                self.failed_counter.inc()
        if batch:
            self.received_meter.mark(len(batch))
            self.handler(batch)
