"""Replay analytics engine: event log / bus -> windowed stat grids.

Reference: the reference's batch-analytics story is "export to Spark"
(sitewhere-spark/SiteWhereReceiver.java:31 subscribing to Hazelcast event
topics); all aggregation happens off-platform. Here replay is first-class
(BASELINE.md config 4 — "Kafka-replay windowed batch analytics"): the
columnar event log (persist/eventlog.py) yields raw column arrays with no
per-event materialization, the host compacts keys and rebases timestamps,
and one accelerator pass (analytics/windows.py) produces the grids.

Two replay sources:
  * `ColumnarEventLog` — vectorized scan, the fast path.
  * an `EventBus` topic — decodes enriched payloads (per-record, control-
    plane rate) and feeds the same kernels; this is the literal
    Kafka-replay flavor used when only the bus log survives.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from sitewhere_tpu.analytics.windows import (
    WindowedStats, compact_keys, dense_key_span, event_type_histogram,
    windowed_stats)
from sitewhere_tpu.model.event import DeviceEventType
from sitewhere_tpu.persist.eventlog import ColumnarEventLog, EventFilter

_N_EVENT_TYPES = 8  # DeviceEventType codes fit comfortably


def _pad_pow2(n: int, floor: int = 8) -> int:
    """Round a grid dimension up to a power of two so replays of similar
    size share one compiled kernel (static-shape bucketing, the same trick
    the ingest packer uses for batch sizes)."""
    out = floor
    while out < n:
        out *= 2
    return out


@dataclass
class WindowReport:
    """Host-side result of one windowed replay."""

    t0_ms: int
    window_ms: int
    n_windows: int
    key_ids: np.ndarray        # raw key per grid row (device_idx or hash id)
    key_tokens: List[str]      # resolved tokens when available ("" otherwise)
    stats: WindowedStats       # [K_padded, W] — rows past len(key_ids) unused
    type_counts: Optional[np.ndarray] = None  # int32 [n_types, W]

    @property
    def num_keys(self) -> int:
        return len(self.key_ids)

    def window_starts(self) -> np.ndarray:
        return self.t0_ms + np.arange(self.n_windows, dtype=np.int64) * \
            self.window_ms

    def series(self, row: int) -> Dict[str, np.ndarray]:
        """One key's per-window series as numpy arrays."""
        return {
            "count": np.asarray(self.stats.count[row, :self.n_windows]),
            "sum": np.asarray(self.stats.sum[row, :self.n_windows]),
            "mean": np.asarray(self.stats.mean[row, :self.n_windows]),
            "min": np.asarray(self.stats.min[row, :self.n_windows]),
            "max": np.asarray(self.stats.max[row, :self.n_windows]),
        }

    def totals(self) -> Dict[str, float]:
        count = np.asarray(self.stats.count)[:self.num_keys, :self.n_windows]
        vsum = np.asarray(self.stats.sum)[:self.num_keys, :self.n_windows]
        n = int(count.sum())
        return {"events": n,
                "mean": float(vsum.sum() / n) if n else float("nan")}


class WindowedAnalyticsEngine:
    """Windowed replay over the columnar event log.

    With a `planner` (serving/planner.py) attached, the `mesh=None`
    default below stops meaning "host kernel" and starts meaning
    "planner-decided": large scans route onto mesh-sharded replay
    (parallel/distributed.py) by default, small ones stay on the host.
    Passing an explicit mesh still forces the sharded path either way.
    """

    def __init__(self, event_log: ColumnarEventLog, planner=None):
        self.event_log = event_log
        self.planner = planner

    def measurement_windows(self, tenant: str, *, window_ms: int = 60_000,
                            mm_name: Optional[str] = None,
                            start_ms: Optional[int] = None,
                            end_ms: Optional[int] = None,
                            area_id: Optional[str] = None,
                            max_windows: int = 4096,
                            with_type_histogram: bool = False,
                            mesh=None, combine: str = "psum"
                            ) -> WindowReport:
        """Per-device windowed stats over measurement values.

        Replaces the Spark-side `reduceByKeyAndWindow` pattern the reference
        delegates to: filter -> column scan -> one segment-reduction pass.
        """
        flt = EventFilter(event_type=DeviceEventType.MEASUREMENT,
                          mm_name=mm_name, area_id=area_id,
                          start_date=start_ms, end_date=end_ms)
        if mesh is None and self.planner is not None:
            # planner-decided routing: the live mesh for large scans,
            # host kernel for small ones (serving/planner.py)
            mesh = self.planner.choose_mesh(tenant, flt)
        # Key on the int32 device_idx column, NOT the token strings:
        # sorting/searching 100k+ Python strings in compact_keys dominated
        # replay cost (≈0.9s of a 1.0s replay at 650k rows); integer
        # compaction is ~20x cheaper. Tokens resolve afterwards, once per
        # UNIQUE key, from each key's first occurrence row.
        names = ["device_idx", "device_token", "event_date", "value"]
        all_flt = (EventFilter(start_date=start_ms, end_date=end_ms,
                               area_id=area_id)
                   if with_type_histogram else None)
        cols = self.event_log.query_columns(tenant, flt, names)
        device_idx = cols["device_idx"].astype(np.int64, copy=True)
        # Control-plane appends may lack an interned index (device_idx 0):
        # those low-rate rows get synthetic negative ids per distinct token
        # so distinct devices never collapse into one key. Hot-path rows all
        # carry real indices and stay on the integer fast path.
        unindexed = np.nonzero(device_idx == 0)[0]
        if len(unindexed):
            token_col = cols["device_token"]
            # a device whose rows arrive via BOTH paths (REST persists with
            # idx 0, fastlane with the real index) must stay ONE key: map
            # idx-0 rows to the real index when this result set has one
            real_rows = np.nonzero(device_idx > 0)[0]
            by_token: Dict[object, int] = {}
            if len(real_rows):
                uniq_real, first_real = np.unique(device_idx[real_rows],
                                                  return_index=True)
                for real_idx, row in zip(uniq_real.tolist(),
                                         real_rows[first_real].tolist()):
                    by_token.setdefault(token_col[row], int(real_idx))
            synthetic: Dict[object, int] = {}
            for row in unindexed:
                token = token_col[row]
                known = by_token.get(token)
                device_idx[row] = (known if known is not None
                                   else synthetic.setdefault(
                                       token, -1 - len(synthetic)))
        report = self._build_report(
            device_idx, cols["event_date"], cols["value"],
            window_ms=window_ms, start_ms=start_ms, end_ms=end_ms,
            max_windows=max_windows,
            hist_cols=(self.event_log.query_columns(
                tenant, all_flt, ["event_type", "event_date"])
                if all_flt is not None else None),
            mesh=mesh, combine=combine)
        if report.num_keys and len(device_idx):
            # first-occurrence row per key id, vectorized: a reversed fancy
            # assignment makes the FIRST occurrence's row index win (later
            # assignments overwrite; reversed order processes row 0 last) —
            # replaces np.unique(return_index) + a 100k-iteration dict loop
            # that dominated the replay tail.
            key_ids = np.asarray(report.key_ids, np.int64)
            token_col = cols["device_token"]
            # key_ids are unique values of device_idx, so device_idx bounds
            # cover both; regime decision shared with compact_keys
            regime = dense_key_span(device_idx)
            if regime is not None:
                lo, span = regime
                first_row = np.full(span, -1, np.int64)
                first_row[(device_idx - lo)[::-1]] = np.arange(
                    len(device_idx) - 1, -1, -1, dtype=np.int64)
                rows = first_row[key_ids - lo].tolist()
            else:  # tiny result sets / huge key spans: dict fallback
                lookup: Dict[int, int] = {}
                for row, k in enumerate(device_idx.tolist()):
                    lookup.setdefault(k, row)
                rows = [lookup.get(int(k), -1) for k in key_ids]
            report.key_tokens = [
                "" if row < 0 or token_col[row] is None
                else str(token_col[row]) for row in rows]
        return report

    @staticmethod
    def _build_report(key_raw: np.ndarray, event_date: np.ndarray,
                      value: np.ndarray, *, window_ms: int,
                      start_ms: Optional[int], end_ms: Optional[int],
                      max_windows: int,
                      hist_cols: Optional[Dict[str, np.ndarray]] = None,
                      tokens: Optional[List[str]] = None,
                      mesh=None, combine: str = "psum") -> WindowReport:
        n = len(event_date)
        # Windows are derived from whatever rows exist — measurement rows
        # normally, histogram rows when the measurement filter matched none
        # (a tenant of pure location/alert traffic still gets its histogram).
        span_dates = event_date
        if n == 0 and hist_cols is not None and len(hist_cols["event_date"]):
            span_dates = hist_cols["event_date"]
        if len(span_dates) == 0:
            empty = WindowedStats(*(np.zeros((0, 0), d) for d in
                                    (np.int32, np.float32, np.float32,
                                     np.float32, np.float32)))
            return WindowReport(t0_ms=start_ms or 0, window_ms=window_ms,
                                n_windows=0, key_ids=np.array([], object),
                                key_tokens=[], stats=empty)
        t0 = int(start_ms if start_ms is not None else span_dates.min())
        t_end = int(end_ms if end_ms is not None else span_dates.max())
        n_windows = max(1, min(max_windows, (t_end - t0) // window_ms + 1))

        def buckets(dates: np.ndarray) -> np.ndarray:
            """int64-safe host bucketing: replays spanning > 2^31 ms cannot
            ride the int32 on-device ts lane, so the bucket index (always
            small — capped by max_windows) is computed here and fed to the
            kernel with window_ms=1 (bucket // 1 == bucket)."""
            rel = dates.astype(np.int64) - t0
            b = rel // window_ms
            return np.where((rel >= 0) & (b < n_windows), b,
                            -1).astype(np.int32)

        valid = (event_date >= t0) & (event_date <= t_end)
        dense, uniq = compact_keys(key_raw, valid)

        K = _pad_pow2(max(len(uniq), 1))
        W = _pad_pow2(int(n_windows))
        if mesh is not None:
            # window-sharded replay across the mesh (the stream analog of
            # sequence/context parallelism — parallel/distributed.py)
            from sitewhere_tpu.parallel.distributed import (
                sharded_windowed_stats)
            stats = sharded_windowed_stats(
                dense, buckets(event_date), value, valid, window_ms=1,
                num_keys=K, n_windows=W, mesh=mesh, combine=combine)
        else:
            stats = windowed_stats(dense, buckets(event_date), value, valid,
                                   window_ms=1, num_keys=K, n_windows=W)
        type_counts = None
        if hist_cols is not None and len(hist_cols["event_date"]):
            h_dates = hist_cols["event_date"]
            h_valid = (h_dates >= t0) & (h_dates <= t_end)
            type_counts = np.asarray(event_type_histogram(
                hist_cols["event_type"], buckets(h_dates), h_valid,
                window_ms=1, n_types=_N_EVENT_TYPES,
                n_windows=W))[:, :n_windows]
        if tokens is not None:
            key_tokens = tokens
        elif uniq.dtype == object:
            key_tokens = [str(u) for u in uniq]
        else:
            key_tokens = [""] * len(uniq)
        return WindowReport(t0_ms=t0, window_ms=window_ms,
                            n_windows=int(n_windows),
                            key_ids=np.asarray(uniq),
                            key_tokens=key_tokens, stats=stats,
                            type_counts=type_counts)


def _decode_measurement_chunk(batch):
    """One poll batch -> (tokens, dates, values) preallocated columns.

    The loop oracle (`unpack_enriched` per record) constructs a
    DeviceEventContext plus a full DeviceEvent dataclass per row and
    appends scalars to Python lists; replay needs exactly three scalars
    per measurement, so this path reads them straight out of the msgpack
    dict into preallocated numpy chunks (no dataclass materialization,
    no per-row list growth). A record whose shape surprises us retries
    through the full decoder before being dropped — decode tolerance is
    unchanged. Returns None when the batch holds no measurements."""
    import msgpack

    m = len(batch)
    tokens = np.empty(m, object)
    dates = np.empty(m, np.int64)
    values = np.empty(m, np.float32)
    k = 0
    measurement = int(DeviceEventType.MEASUREMENT)
    for record in batch:
        try:
            event = msgpack.unpackb(record.value, raw=False)["event"]
            etype = event["event_type"]
            edate = event["event_date"]
            evalue = event.get("value", 0.0)
            token = event.get("device_id") or ""
        except Exception:
            try:  # slow-path retry: the oracle's full decode
                from sitewhere_tpu.pipeline.enrichment import unpack_enriched
                _, ev = unpack_enriched(record.value)
                etype, edate = int(ev.event_type), ev.event_date
                evalue = getattr(ev, "value", 0.0)
                token = ev.device_id or ""
            except Exception:
                continue
        if etype != measurement:
            continue
        tokens[k] = token
        dates[k] = int(edate)
        values[k] = float(evalue or 0.0)
        k += 1
    if k == 0:
        return None
    return tokens[:k], dates[:k], values[:k]


class BusReplayAnalytics:
    """The literal Kafka-replay flavor: re-consume an enriched topic from
    offset zero into columns, then run the same windowed kernels.

    Reference analogue: re-attaching a Spark job to the Hazelcast topic and
    letting it rebuild windows from the retained stream.
    """

    def __init__(self, bus, naming=None):
        from sitewhere_tpu.runtime.bus import TopicNaming
        self.bus = bus
        self.naming = naming or TopicNaming()

    def replay_measurements(self, tenant: str, *, window_ms: int = 60_000,
                            group_id: str = "analytics-replay",
                            max_windows: int = 4096) -> WindowReport:
        topic = self.naming.inbound_enriched_events(tenant)
        consumer = self.bus.consumer(topic, group_id)
        consumer.seek_to_beginning()
        token_chunks: List[np.ndarray] = []
        date_chunks: List[np.ndarray] = []
        value_chunks: List[np.ndarray] = []
        while True:
            batch = consumer.poll(8192)
            if not batch:
                break
            chunk = _decode_measurement_chunk(batch)
            if chunk is not None:
                token_chunks.append(chunk[0])
                date_chunks.append(chunk[1])
                value_chunks.append(chunk[2])
        if not token_chunks:
            return WindowedAnalyticsEngine._build_report(
                np.array([], np.int64), np.array([], np.int64),
                np.array([], np.float32), window_ms=window_ms,
                start_ms=None, end_ms=None, max_windows=max_windows,
                tokens=[])
        all_tokens = np.concatenate(token_chunks)
        # batch token interning replacing the per-row dict setdefault:
        # one np.unique pass, then a rank remap so key ids keep the
        # original FIRST-APPEARANCE numbering (np.unique sorts
        # lexically; downstream key order must not change).
        uniq, first, inverse = np.unique(all_tokens, return_index=True,
                                         return_inverse=True)
        rank = np.empty(len(uniq), np.int64)
        rank[np.argsort(first, kind="stable")] = np.arange(
            len(uniq), dtype=np.int64)
        keys = rank[inverse]
        tokens_arr = np.empty(len(uniq), object)
        tokens_arr[rank] = uniq
        return WindowedAnalyticsEngine._build_report(
            keys, np.concatenate(date_chunks),
            np.concatenate(value_chunks), window_ms=window_ms,
            start_ms=None, end_ms=None, max_windows=max_windows,
            tokens=[str(t) for t in tokens_arr])
