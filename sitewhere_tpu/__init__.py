"""sitewhere_tpu: a TPU-native IoT application-enablement framework.

A ground-up rebuild of the capabilities of SiteWhere 2.0 (the reference Java
microservice platform) designed TPU-first: the hot event path
(ingest -> validate -> rule-eval -> device-state) executes as a single fused
JAX/XLA step over HBM-resident event tensors, sharded over a TPU mesh with
ICI collectives, while the control plane (registry, tenants, users, REST API,
command delivery) runs as conventional host-side Python.

Package map (reference layer -> here):
  L0 API/model contract  (sitewhere-core-api)        -> sitewhere_tpu.model
  L1 core runtime        (sitewhere-microservice,
                          sitewhere-core-lifecycle)  -> sitewhere_tpu.runtime
  L2 communication       (Kafka + gRPC + MQTT)       -> sitewhere_tpu.runtime.bus (data plane),
                                                        sitewhere_tpu.transport (device wire)
  L3 persistence         (mongo/hbase/...)           -> sitewhere_tpu.persist, sitewhere_tpu.registry
  L4 domain services     (service-*)                 -> sitewhere_tpu.pipeline (hot path on TPU),
                                                        sitewhere_tpu.services (control plane)
  L5 edge APIs           (service-web-rest, client)  -> sitewhere_tpu.api
  TPU compute            (n/a in reference)          -> sitewhere_tpu.ops, sitewhere_tpu.parallel
"""

__version__ = "0.1.0"
