"""Rule-program compiler: a CEP-lite DSL -> fixed-shape tensor programs.

The fused step's built-in rule surface is two stateless primitives
(ops/threshold.py, ops/geofence.py) firing independently per event;
anything composite — "temp > 90 AND humidity < 20 for 30 s", debounce,
hysteresis, rate-of-change — used to fall back to the host-side
RuleProcessor extension point at control-plane rates (the reference's
ZoneTest/Groovy story). Following the compile-a-declarative-spec-into-a-
fixed-shape-program pattern (TensorFlow's dataflow-program compilation,
arXiv:1605.08695; tf.data's static pipeline graphs, arXiv:2101.12127),
this module compiles a small declarative spec into static SoA program
tables — predicate opcodes, operand slot indices, constants, a
binarized boolean-combinator tree, temporal-operator params — padded to
a static max-program bucket the way the ingest packer buckets batch
sizes. ops/stateful.py evaluates the tables vectorized over every
(device, program) pair inside the fused pjit step, with per-(device,
program) state carried in HBM across steps.

Spec shape (JSON; `when` is the expression tree):

    {"token": "overheat-dry", "tenant_token": "", "device_type_token": "",
     "alert_type": "rule.program", "alert_level": "WARNING",
     "alert_message": "...", "active": true,
     "when": {"all": [
         {"pred": "value", "measurement": "temp", "op": ">", "value": 90},
         {"for_duration": {"pred": "value", "measurement": "humidity",
                           "op": "<", "value": 20}, "ms": 30000}]}}

Node kinds:
  predicates   {"pred": "value" | "ewma" | "rate", "measurement": name,
                "op": one of > >= < <= == !=, "value": float,
                "alpha": float (ewma only, default 0.2)}
  combinators  {"all": [nodes]}  {"any": [nodes]}  {"not": node}
  temporal     {"for_duration": node, "ms": int}
               {"debounce": node, "count": int}
               {"hysteresis": {"arm": node, "disarm": node}}

Semantics are per-fused-step (docs/RULE_PROGRAMS.md): a device's
observation tick is a step in which it had at least one valid
measurement event on a tracked slot; predicates read the post-fold
last-measurement state, so conditions over measurements arriving in
different events compose naturally. A program fires on the RISING EDGE
of its root expression at an observation tick; steps where the root
stays true count as suppressions (per-program counters).

Validation is structural and loud: an invalid spec raises
RuleProgramError (a 409 SiteWhereError) naming the offending node path
("when.all[1].debounce"), never a stack trace — on both the REST and
the replicated-apply paths.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
from flax import struct

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.ops.threshold import ThresholdOp

# static buckets: one cached jit program per (bucket, batch) shape, like
# every other static shape in the pipeline. Programs, nodes-per-program
# and stateful-nodes-per-program all pad to these.
DEFAULT_MAX_PROGRAMS = 32
MAX_PROGRAM_BUCKET = 256       # program slot id travels in 8 lane bits
DEFAULT_PROGRAM_NODES = 16
DEFAULT_STATE_SLOTS = 8
MAX_ALERT_LEVEL = 15           # program alert level travels in 4 lane bits


class ProgramOp:
    """Node opcodes of the compiled program table (evaluation order is
    node-slot order; children always sit at lower slots)."""

    NOP = 0
    VALUE = 1        # cmp(last_measurement[mm], const)
    EWMA = 2         # cmp(ewma_alpha(mm), const)        [stateful]
    RATE = 3         # cmp(d(mm)/dt per second, const)    [stateful]
    NOT = 4          # ~lhs
    AND = 5          # lhs & rhs
    OR = 6           # lhs | rhs
    DEBOUNCE = 7     # lhs held for >= iparam consecutive ticks [stateful]
    FOR_DURATION = 8  # lhs held continuously for >= iparam ms  [stateful]
    HYSTERESIS = 9   # latch: set by lhs (arm), cleared by rhs (disarm)
                     #                                     [stateful]

    STATEFUL = (EWMA, RATE, DEBOUNCE, FOR_DURATION, HYSTERESIS)


class RuleProgramError(SiteWhereError):
    """Invalid rule-program spec: names the offending node so the 409
    is actionable on REST and replicated-apply paths alike."""

    def __init__(self, message: str, node_path: str = "when"):
        super().__init__(f"invalid rule program at {node_path}: {message}",
                         ErrorCode.GENERIC, http_status=409)
        self.node_path = node_path


@struct.dataclass
class RuleProgramTable:
    """SoA program tables; per-program columns [P], per-node [P, N].

    `epoch` is a per-slot generation number: the stateful kernel zeroes a
    slot's RuleStateTensors lanes when its stored generation differs, so
    installing a new program into a recycled slot resets temporal state
    INSIDE the fused step — lockstep-safe on multi-host meshes (no
    out-of-band device mutation)."""

    active: np.ndarray           # bool [P]
    tenant_idx: np.ndarray       # int32 [P], 0 = any tenant
    device_type_idx: np.ndarray  # int32 [P], 0 = any device type
    alert_level: np.ndarray      # int32 [P]
    alert_type_idx: np.ndarray   # int32 [P]
    root: np.ndarray             # int32 [P] root node slot
    epoch: np.ndarray            # int32 [P] state generation

    opcode: np.ndarray           # int32 [P, N] ProgramOp
    mm_idx: np.ndarray           # int32 [P, N] measurement slot (< M)
    lhs: np.ndarray              # int32 [P, N] child node slot
    rhs: np.ndarray              # int32 [P, N] second child node slot
    cmp_op: np.ndarray           # int32 [P, N] ThresholdOp
    fconst: np.ndarray           # float32 [P, N] compare constant
    falpha: np.ndarray           # float32 [P, N] ewma alpha
    iparam: np.ndarray           # int32 [P, N] debounce count / duration ms
    state_slot: np.ndarray       # int32 [P, N] RuleStateTensors lane

    @property
    def num_programs(self) -> int:
        return self.active.shape[0]

    @property
    def num_nodes(self) -> int:
        return self.opcode.shape[1]


def empty_program_table(max_programs: int = DEFAULT_MAX_PROGRAMS,
                        max_nodes: int = DEFAULT_PROGRAM_NODES
                        ) -> RuleProgramTable:
    P, N = max_programs, max_nodes
    zp = np.zeros(P, np.int32)
    zn = np.zeros((P, N), np.int32)
    return RuleProgramTable(
        active=np.zeros(P, bool), tenant_idx=zp, device_type_idx=zp.copy(),
        alert_level=zp.copy(), alert_type_idx=zp.copy(), root=zp.copy(),
        epoch=zp.copy(), opcode=zn, mm_idx=zn.copy(), lhs=zn.copy(),
        rhs=zn.copy(), cmp_op=zn.copy(),
        fconst=np.zeros((P, N), np.float32),
        falpha=np.zeros((P, N), np.float32), iparam=zn.copy(),
        state_slot=zn.copy())


# ---------------------------------------------------------------------------
# spec validation / normalization (wire + store form)
# ---------------------------------------------------------------------------

_COMBINATORS = ("all", "any", "not")
_TEMPORALS = ("for_duration", "debounce", "hysteresis")
_PREDICATES = ("value", "ewma", "rate")


def _require(cond: bool, message: str, path: str) -> None:
    if not cond:
        raise RuleProgramError(message, path)


def _validate_node(node, path: str) -> None:
    """Structural validation of one expression node (no engine context:
    measurement-slot range checks happen at compile time)."""
    _require(isinstance(node, dict), "node must be an object", path)
    if "pred" in node:
        kind = node.get("pred")
        _require(kind in _PREDICATES,
                 f"unknown opcode {kind!r} (one of {_PREDICATES})", path)
        name = node.get("measurement")
        _require(isinstance(name, str) and bool(name),
                 "predicate requires a 'measurement' name", path)
        op = node.get("op", ">")
        _require(op in ThresholdOp.BY_NAME,
                 f"unknown operator {op!r} (one of "
                 f"{sorted(ThresholdOp.BY_NAME)})", path)
        _require(isinstance(node.get("value"), (int, float))
                 and not isinstance(node.get("value"), bool),
                 "predicate requires a numeric 'value'", path)
        if kind == "ewma":
            alpha = node.get("alpha", 0.2)
            _require(isinstance(alpha, (int, float))
                     and 0.0 < float(alpha) <= 1.0,
                     "ewma 'alpha' must be in (0, 1]", path)
        return
    keys = [k for k in node
            if k in _COMBINATORS or k in _TEMPORALS]
    _require(len(keys) == 1,
             "node must be exactly one of pred/all/any/not/"
             "for_duration/debounce/hysteresis", path)
    kind = keys[0]
    sub = node[kind]
    if kind in ("all", "any"):
        _require(isinstance(sub, list) and len(sub) >= 1,
                 f"'{kind}' requires a non-empty list", path)
        for i, child in enumerate(sub):
            _validate_node(child, f"{path}.{kind}[{i}]")
    elif kind == "not":
        _validate_node(sub, f"{path}.not")
    elif kind == "hysteresis":
        _require(isinstance(sub, dict) and "arm" in sub and "disarm" in sub,
                 "'hysteresis' requires {'arm': node, 'disarm': node}", path)
        _validate_node(sub["arm"], f"{path}.hysteresis.arm")
        _validate_node(sub["disarm"], f"{path}.hysteresis.disarm")
    elif kind == "debounce":
        _validate_node(sub, f"{path}.debounce")
        count = node.get("count")
        _require(isinstance(count, int) and not isinstance(count, bool)
                 and count >= 1, "'debounce' requires integer count >= 1",
                 path)
    elif kind == "for_duration":
        _validate_node(sub, f"{path}.for_duration")
        ms = node.get("ms")
        _require(isinstance(ms, int) and not isinstance(ms, bool)
                 and ms >= 0, "'for_duration' requires integer ms >= 0",
                 path)


def program_from_dict(data: Dict) -> Dict:
    """Validate + normalize a wire/store spec into its canonical dict.
    Raises RuleProgramError (409, names the node) on anything a compile
    could not turn into table rows."""
    from sitewhere_tpu.model.event import AlertLevel

    _require(isinstance(data, dict), "spec must be an object", "spec")
    token = data.get("token")
    _require(isinstance(token, str) and bool(token),
             "program requires a string token", "spec.token")
    level = data.get("alert_level", int(AlertLevel.WARNING))
    try:
        level = (AlertLevel[level]
                 if isinstance(level, str) and not level.lstrip("-").isdigit()
                 else AlertLevel(int(level)))
    except (KeyError, ValueError, TypeError):
        raise RuleProgramError(f"invalid alert_level {level!r}",
                               "spec.alert_level")
    _require(0 <= int(level) <= MAX_ALERT_LEVEL,
             f"alert_level must fit {MAX_ALERT_LEVEL}", "spec.alert_level")
    for field in ("tenant_token", "device_type_token", "alert_type",
                  "alert_message"):
        value = data.get(field, "")
        _require(isinstance(value, str),
                 f"'{field}' must be a string", f"spec.{field}")
    when = data.get("when")
    _require(when is not None, "program requires a 'when' expression",
             "spec.when")
    _validate_node(when, "when")
    return {
        "token": token,
        "tenant_token": data.get("tenant_token", "") or "",
        "device_type_token": data.get("device_type_token", "") or "",
        "alert_type": data.get("alert_type", "") or "rule.program",
        "alert_level": int(level),
        "alert_message": data.get("alert_message", "") or "",
        "active": bool(data.get("active", True)),
        "when": when,
    }


# ---------------------------------------------------------------------------
# compilation: expression tree -> node rows at one program slot
# ---------------------------------------------------------------------------

class _ProgramBuilder:
    """Flattens one expression tree into post-order node rows; children
    always land at lower slots than their parents, so the evaluator is a
    single unrolled pass over node slots."""

    def __init__(self, token: str, max_nodes: int, max_state_slots: int):
        self.token = token
        self.max_nodes = max_nodes
        self.max_state_slots = max_state_slots
        self.rows: List[Dict] = []
        self.next_state_slot = 0

    def _alloc_node(self, path: str) -> int:
        if len(self.rows) >= self.max_nodes:
            raise RuleProgramError(
                f"program over the static bucket: more than "
                f"{self.max_nodes} nodes", path)
        self.rows.append({})
        return len(self.rows) - 1

    def _alloc_state(self, path: str) -> int:
        if self.next_state_slot >= self.max_state_slots:
            raise RuleProgramError(
                f"program over the static bucket: more than "
                f"{self.max_state_slots} stateful nodes", path)
        slot = self.next_state_slot
        self.next_state_slot += 1
        return slot

    def emit(self, node: Dict, path: str, intern_measurement,
             measurement_slots: int) -> int:
        """Returns the node slot holding this subtree's output."""
        if "pred" in node:
            mm = intern_measurement(node["measurement"])
            if not (0 < mm < measurement_slots):
                raise RuleProgramError(
                    f"operand slot out of range: measurement "
                    f"{node['measurement']!r} interned to slot {mm}, "
                    f"tracked slots are 1..{measurement_slots - 1}", path)
            opcode = {"value": ProgramOp.VALUE, "ewma": ProgramOp.EWMA,
                      "rate": ProgramOp.RATE}[node["pred"]]
            row = {"opcode": opcode, "mm_idx": mm,
                   "cmp_op": ThresholdOp.BY_NAME[node.get("op", ">")],
                   "fconst": float(node["value"])}
            if opcode == ProgramOp.EWMA:
                row["falpha"] = float(node.get("alpha", 0.2))
            if opcode in ProgramOp.STATEFUL:
                row["state_slot"] = self._alloc_state(path)
            slot = self._alloc_node(path)
            self.rows[slot] = row
            return slot
        kind = next(k for k in node if k in _COMBINATORS + _TEMPORALS)
        if kind in ("all", "any"):
            op = ProgramOp.AND if kind == "all" else ProgramOp.OR
            children = [self.emit(child, f"{path}.{kind}[{i}]",
                                  intern_measurement, measurement_slots)
                        for i, child in enumerate(node[kind])]
            out = children[0]
            for child in children[1:]:  # left-fold binarization
                slot = self._alloc_node(path)
                self.rows[slot] = {"opcode": op, "lhs": out, "rhs": child}
                out = slot
            return out
        if kind == "not":
            child = self.emit(node["not"], f"{path}.not",
                              intern_measurement, measurement_slots)
            slot = self._alloc_node(path)
            self.rows[slot] = {"opcode": ProgramOp.NOT, "lhs": child}
            return slot
        if kind == "hysteresis":
            arm = self.emit(node["hysteresis"]["arm"],
                            f"{path}.hysteresis.arm",
                            intern_measurement, measurement_slots)
            disarm = self.emit(node["hysteresis"]["disarm"],
                               f"{path}.hysteresis.disarm",
                               intern_measurement, measurement_slots)
            slot = self._alloc_node(path)
            self.rows[slot] = {"opcode": ProgramOp.HYSTERESIS, "lhs": arm,
                               "rhs": disarm,
                               "state_slot": self._alloc_state(path)}
            return slot
        child = self.emit(node[kind], f"{path}.{kind}",
                          intern_measurement, measurement_slots)
        slot = self._alloc_node(path)
        if kind == "debounce":
            self.rows[slot] = {"opcode": ProgramOp.DEBOUNCE, "lhs": child,
                               "iparam": int(node["count"]),
                               "state_slot": self._alloc_state(path)}
        else:
            self.rows[slot] = {"opcode": ProgramOp.FOR_DURATION,
                               "lhs": child, "iparam": int(node["ms"]),
                               "state_slot": self._alloc_state(path)}
        return slot


def compile_program_into(table: RuleProgramTable, slot: int, spec: Dict,
                         epoch: int, *, intern_measurement,
                         intern_alert_type, lookup_tenant,
                         lookup_device_type, measurement_slots: int,
                         max_state_slots: int = DEFAULT_STATE_SLOTS) -> None:
    """Compile one normalized spec into program slot `slot` of `table`.

    The intern/lookup callables bind the spec's names to the engine's
    interners (pipeline/engine.py passes its packer + registry). A
    scoping token that does not resolve deactivates the program rather
    than silently widening to "any" — the same rule the threshold
    compiler applies."""
    spec = program_from_dict(spec)  # idempotent; applies on every path
    builder = _ProgramBuilder(spec["token"], table.num_nodes,
                              max_state_slots)
    root = builder.emit(spec["when"], "when", intern_measurement,
                        measurement_slots)

    active = spec["active"]
    tenant_idx = dtype_idx = 0
    if spec["tenant_token"]:
        tenant_idx = lookup_tenant(spec["tenant_token"])
        active = active and tenant_idx > 0
    if spec["device_type_token"]:
        dtype_idx = lookup_device_type(spec["device_type_token"])
        active = active and dtype_idx > 0

    # clear the slot before writing (a recycled slot keeps no stale rows)
    for name in ("opcode", "mm_idx", "lhs", "rhs", "cmp_op", "iparam",
                 "state_slot"):
        getattr(table, name)[slot, :] = 0
    table.fconst[slot, :] = 0.0
    table.falpha[slot, :] = 0.0
    for j, row in enumerate(builder.rows):
        table.opcode[slot, j] = row.get("opcode", ProgramOp.NOP)
        table.mm_idx[slot, j] = row.get("mm_idx", 0)
        table.lhs[slot, j] = row.get("lhs", 0)
        table.rhs[slot, j] = row.get("rhs", 0)
        table.cmp_op[slot, j] = row.get("cmp_op", 0)
        table.fconst[slot, j] = row.get("fconst", 0.0)
        table.falpha[slot, j] = row.get("falpha", 0.0)
        table.iparam[slot, j] = row.get("iparam", 0)
        table.state_slot[slot, j] = row.get("state_slot", 0)
    table.active[slot] = active
    table.tenant_idx[slot] = tenant_idx
    table.device_type_idx[slot] = dtype_idx
    table.alert_level[slot] = spec["alert_level"]
    table.alert_type_idx[slot] = intern_alert_type(spec["alert_type"])
    table.root[slot] = root
    table.epoch[slot] = epoch


def dry_run_compile(spec: Dict, *, measurement_slots: int,
                    max_nodes: int = DEFAULT_PROGRAM_NODES,
                    max_state_slots: int = DEFAULT_STATE_SLOTS,
                    intern_measurement=None) -> Dict:
    """Full validation WITHOUT touching a live table: used by the REST
    create and the replicated-apply paths so a bad spec 409s before any
    store/engine mutation. Returns the normalized spec. When no interner
    is supplied, measurement names validate structurally only (slot 1
    assumed) — the engine-side compile still enforces the range."""
    normalized = program_from_dict(spec)
    table = empty_program_table(1, max_nodes)
    compile_program_into(
        table, 0, normalized, epoch=1,
        intern_measurement=intern_measurement or (lambda name: 1),
        intern_alert_type=lambda name: 0,
        lookup_tenant=lambda token: 1,
        lookup_device_type=lambda token: 1,
        measurement_slots=measurement_slots,
        max_state_slots=max_state_slots)
    return normalized
