"""Host-side rule processors: the user extension point for custom rules.

Reference: service-rule-processing — RuleProcessorsManager hosts N
IRuleProcessors, each wrapped in KafkaRuleProcessorHost.java:47 with its own
consumer group (:78) on the enriched topic, dispatching by event type
(attemptToProcess :144). Base RuleProcessor.java:31 has no-op hooks
(onLocation/onAlert/... :58-77); the shipped impl is
ZoneTestRuleProcessor.java:33 (JTS point-in-polygon geofencing).

TPU-first split: built-in threshold/geofence rules run VECTORIZED inside the
fused pjit step (ops/threshold.py, ops/geofence.py) — that is the 1M ev/s
path. This module is the *extension point* for arbitrary Python rule logic
at control-plane rates, same SPI shape as the reference.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.model.event import (
    AlertLevel, AlertSource, DeviceAlert, DeviceCommandInvocation,
    DeviceCommandResponse, DeviceEvent, DeviceEventContext, DeviceLocation,
    DeviceMeasurement, DeviceStateChange, dispatch_event)
from sitewhere_tpu.runtime.bus import ConsumerHost, EventBus, Record, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry

LOGGER = logging.getLogger("sitewhere.rules")


class RuleProcessor(LifecycleComponent):
    """Base rule processor (RuleProcessor.java:31): override the hooks."""

    def __init__(self, processor_id: str):
        super().__init__(f"rule-processor:{processor_id}")
        self.processor_id = processor_id

    def process(self, context: DeviceEventContext, event: DeviceEvent) -> None:
        dispatch_event(self, context, event)

    # no-op hooks (RuleProcessor.java:58-77)
    def on_measurement(self, context, event: DeviceMeasurement) -> None: ...
    def on_location(self, context, event: DeviceLocation) -> None: ...
    def on_alert(self, context, event: DeviceAlert) -> None: ...
    def on_command_invocation(self, context,
                              event: DeviceCommandInvocation) -> None: ...
    def on_command_response(self, context,
                            event: DeviceCommandResponse) -> None: ...
    def on_state_change(self, context, event: DeviceStateChange) -> None: ...
    def on_stream_data(self, context, event) -> None: ...


class RuleProcessorHost(LifecycleComponent):
    """Own consumer group on the enriched topic per processor
    (KafkaRuleProcessorHost.java:47,:78)."""

    def __init__(self, bus: EventBus, processor: RuleProcessor,
                 tenant: str = "default",
                 naming: Optional[TopicNaming] = None,
                 metrics: Optional[MetricsRegistry] = None):
        super().__init__(f"rule-host:{processor.processor_id}")
        self.bus = bus
        self.processor = processor
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.add_nested(processor)
        m = (metrics or MetricsRegistry()).scoped(
            f"rules.{processor.processor_id}")
        self.processed_meter = m.meter("processed")
        self.failed_counter = m.counter("failed")
        self._host = ConsumerHost(
            bus, self.naming.inbound_enriched_events(tenant),
            group_id=f"rule-processor-{processor.processor_id}-{tenant}",
            handler=self.process)

    def on_start(self, monitor) -> None:
        self._host.start()

    def on_stop(self, monitor) -> None:
        self._host.stop()

    def process(self, records: List[Record]) -> None:
        """attemptToProcess :144 per record; public for synchronous tests."""
        # deferred: the pipeline package imports ops/stateful.py, which
        # imports this package's compiler — a module-level import here
        # closes that cycle whenever ops.stateful is imported first
        from sitewhere_tpu.pipeline.enrichment import unpack_enriched
        for record in records:
            try:
                context, event = unpack_enriched(record.value)
            except Exception:
                self.failed_counter.inc()
                continue
            try:
                self.processor.process(context, event)
                self.processed_meter.mark(1)
            except Exception:
                self.failed_counter.inc()
                LOGGER.exception("rule processor %s failed",
                                 self.processor.processor_id)


class ScriptedRuleProcessor(RuleProcessor):
    """User-script rule processor (the reference's Groovy rule processor
    role): every enriched event dispatches to the script's entry callable
    `(context, event)`. Wired from the rule management surface with a
    hot-swappable ScriptManager proxy (runtime/scripts.py resolve), so
    activating a new script version retargets live processors.

    HOST-LOCAL and non-durable by design: the processor wraps a live
    Python callable on THIS process; it re-installs from config at boot
    (`__main__._apply_scripted_rule`) but, unlike fused rules, is not
    checkpointed or gossiped. `script_id` records which script backs it
    (operator audit surface)."""

    def __init__(self, processor_id: str, handler,
                 script_id: str = ""):
        super().__init__(processor_id)
        self.handler = handler
        self.script_id = script_id

    def process(self, context: DeviceEventContext,
                event: DeviceEvent) -> None:
        self.handler(context, event)


class RuleProcessorsManager(LifecycleComponent):
    """Hosts all rule processors of one tenant (RuleProcessorsManager)."""

    def __init__(self, bus: EventBus, tenant: str = "default",
                 naming: Optional[TopicNaming] = None):
        super().__init__("rule-processors-manager")
        self.bus = bus
        self.tenant = tenant
        self.naming = naming or TopicNaming()
        self.hosts: List[RuleProcessorHost] = []

    def add_processor(self, processor: RuleProcessor) -> RuleProcessorHost:
        """Install a processor; atomic duplicate-id check, and live start
        when the manager is running (REST rule management). A failed live
        start rolls the install back so a retry is not met with a
        duplicate error for a rule that never ran. Mutations hold the
        component _lock — lifecycle start/stop iterate _nested under it."""
        from sitewhere_tpu.errors import DuplicateTokenError

        host = RuleProcessorHost(self.bus, processor, self.tenant, self.naming)
        with self._lock:
            if any(h.processor.processor_id == processor.processor_id
                   for h in self.hosts):
                raise DuplicateTokenError(
                    f"rule processor '{processor.processor_id}' already "
                    f"exists")
            self.hosts.append(host)
            self._nested.append(host)
            if host.tenant_id is None:  # add_nested's propagation
                host.tenant_id = self.tenant_id
            live = self.is_running()
        if live:
            try:
                host.start()
            except Exception:
                with self._lock:
                    if host in self.hosts:
                        self.hosts.remove(host)
                    if host in self._nested:
                        self._nested.remove(host)
                raise
        return host

    def get_processor(self, processor_id: str) -> Optional[RuleProcessor]:
        with self._lock:
            for host in self.hosts:
                if host.processor.processor_id == processor_id:
                    return host.processor
        return None

    def list_processors(self) -> List[RuleProcessorHost]:
        with self._lock:
            return list(self.hosts)

    def remove_processor(self, processor_id: str) -> bool:
        """Stop + detach one processor's host (live uninstall)."""
        with self._lock:
            target = None
            for host in self.hosts:
                if host.processor.processor_id == processor_id:
                    target = host
                    break
            if target is None:
                return False
            self.hosts.remove(target)
            if target in self._nested:
                self._nested.remove(target)
        target.stop()  # outside the lock: stop joins consumer threads
        return True


def point_in_polygon(lat: float, lon: float,
                     vertices: np.ndarray) -> bool:
    """Crossing-number containment for one point against [N,2] (lat,lon)
    vertices — the scalar twin of ops/geofence.points_in_zones."""
    inside = False
    n = len(vertices)
    for i in range(n):
        y1, x1 = vertices[i]
        y2, x2 = vertices[(i + 1) % n]
        if (x1 > lon) != (x2 > lon):
            t = (lon - x1) / (x2 - x1)
            if lat < y1 + t * (y2 - y1):
                inside = not inside
    return inside


class ZoneTestRuleProcessor(RuleProcessor):
    """Geofence rule at the extension point (ZoneTestRuleProcessor.java:33):
    per-location containment test against a cached zone polygon, firing a
    DeviceAlert through event management on condition match.

    Prefer the fused GeofenceRule (pipeline/engine.py) for volume; this
    exists for SPI parity and custom per-event logic.
    """

    def __init__(self, processor_id: str, registry, events,
                 zone_token: str, condition: str = "outside",
                 alert_type: str = "zone.violation",
                 alert_level: AlertLevel = AlertLevel.WARNING,
                 alert_message: str = ""):
        super().__init__(processor_id)
        self.registry = registry
        self.events = events
        self.zone_token = zone_token
        self.condition = condition
        self.alert_type = alert_type
        self.alert_level = alert_level
        self.alert_message = alert_message
        self._polygon: Optional[np.ndarray] = None  # getZonePolygon :72 cache

    def _zone_polygon(self) -> np.ndarray:
        if self._polygon is None:
            zone = self.registry.get_zone_by_token(self.zone_token)
            self._polygon = np.array(
                [(p.latitude, p.longitude) for p in zone.bounds], np.float64)
        return self._polygon

    def on_location(self, context, event: DeviceLocation) -> None:
        contained = point_in_polygon(event.latitude, event.longitude,
                                     self._zone_polygon())
        fired = contained if self.condition == "inside" else not contained
        if fired:
            self.events.add_alerts(context.assignment_id, DeviceAlert(
                device_id=context.device_token, source=AlertSource.SYSTEM,
                level=self.alert_level, type=self.alert_type,
                message=self.alert_message or
                f"zone condition '{self.condition}' met for {self.zone_token}",
                event_date=event.event_date))
