"""Streaming rule processing (reference: service-rule-processing)."""

from sitewhere_tpu.rules.processor import (
    RuleProcessor, RuleProcessorHost, RuleProcessorsManager,
    ZoneTestRuleProcessor)

__all__ = ["RuleProcessor", "RuleProcessorHost", "RuleProcessorsManager",
           "ZoneTestRuleProcessor"]
