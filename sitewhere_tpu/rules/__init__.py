"""Streaming rule processing (reference: service-rule-processing)."""

from sitewhere_tpu.rules.processor import (
    RuleProcessor, RuleProcessorHost, RuleProcessorsManager,
    ScriptedRuleProcessor, ZoneTestRuleProcessor)

__all__ = ["RuleProcessor", "RuleProcessorHost", "RuleProcessorsManager",
           "ScriptedRuleProcessor", "ZoneTestRuleProcessor"]
