"""Streaming rule processing (reference: service-rule-processing) plus
the CEP-lite rule-program compiler (docs/RULE_PROGRAMS.md)."""

from sitewhere_tpu.rules.compiler import (
    ProgramOp, RuleProgramError, RuleProgramTable, program_from_dict)
from sitewhere_tpu.rules.processor import (
    RuleProcessor, RuleProcessorHost, RuleProcessorsManager,
    ScriptedRuleProcessor, ZoneTestRuleProcessor)

__all__ = ["RuleProcessor", "RuleProcessorHost", "RuleProcessorsManager",
           "ScriptedRuleProcessor", "ZoneTestRuleProcessor",
           "ProgramOp", "RuleProgramError", "RuleProgramTable",
           "program_from_dict"]
