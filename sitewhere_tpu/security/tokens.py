"""JWT issuance + validation (HMAC-SHA256).

Reference: sitewhere-microservice security/TokenManagement.java — issues JWTs
carrying username + granted authorities, validated by JwtServerInterceptor on
every gRPC call and TokenAuthenticationFilter on REST. Same claim shape here:
``sub`` (username), ``auth`` (authority list), ``iat``/``exp``.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from typing import Dict, List, Optional


class InvalidTokenError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _unb64url(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


class TokenManagement:
    """Issue + validate HS256 JWTs (TokenManagement.java:
    generateToken/getClaimsForToken)."""

    # bound on the validated-claims cache; parse+HMAC per request is cheap
    # but not free, and the cache is what user-mutation replication
    # invalidates (multitenant/replication.py)
    _CACHE_MAX = 4096

    def __init__(self, secret: Optional[bytes] = None,
                 expiration_minutes: int = 60, issuer: str = "sitewhere"):
        self.secret = secret or os.urandom(32)
        self.expiration_minutes = expiration_minutes
        self.issuer = issuer
        self._cache: Dict[str, Dict] = {}
        # username -> revocation cut (ms): tokens issued at or before the
        # cut are rejected — a DELETED user's tokens die cluster-wide
        # instead of riding out their expiry window
        self._revoked: Dict[str, int] = {}

    def invalidate_user(self, username: str, revoke: bool = False) -> None:
        """Drop cached auth state for `username`; with `revoke`, also
        reject every token issued up to now (user deletion). Called on
        local AND replicated user mutations (instance wiring)."""
        if not username:
            return
        import time as _time

        self._cache = {tok: claims for tok, claims in self._cache.items()
                       if claims.get("sub") != username}
        if revoke:
            cut = int(_time.time() * 1000)
            self._revoked[username] = max(self._revoked.get(username, 0),
                                          cut)

    def _sign(self, signing_input: bytes) -> bytes:
        return hmac.new(self.secret, signing_input, hashlib.sha256).digest()

    def generate_token(self, username: str,
                       authorities: Optional[List[str]] = None,
                       expiration_minutes: Optional[int] = None) -> str:
        now = int(time.time())
        minutes = (expiration_minutes if expiration_minutes is not None
                   else self.expiration_minutes)
        header = _b64url(json.dumps(
            {"alg": "HS256", "typ": "JWT"}, separators=(",", ":")).encode())
        payload = _b64url(json.dumps({
            "sub": username, "iss": self.issuer,
            "auth": authorities or [], "iat": now,
            "exp": now + minutes * 60}, separators=(",", ":")).encode())
        signing_input = f"{header}.{payload}".encode("ascii")
        return f"{header}.{payload}.{_b64url(self._sign(signing_input))}"

    def get_claims(self, token: str) -> Dict:
        claims = self._cache.get(token)
        if claims is None:
            try:
                header, payload, signature = token.split(".")
            except ValueError:
                raise InvalidTokenError("malformed token")
            signing_input = f"{header}.{payload}".encode("ascii")
            if not hmac.compare_digest(_unb64url(signature),
                                       self._sign(signing_input)):
                raise InvalidTokenError("bad signature")
            claims = json.loads(_unb64url(payload))
            if len(self._cache) >= self._CACHE_MAX:
                self._cache.clear()  # bounded; rebuilt on demand
            self._cache[token] = claims
        # exp + revocation checked on EVERY read, cached or not
        if claims.get("exp", 0) < time.time():
            self._cache.pop(token, None)
            raise InvalidTokenError("token expired")
        cut = self._revoked.get(claims.get("sub", ""))
        if cut is not None and int(claims.get("iat", 0)) * 1000 <= cut:
            self._cache.pop(token, None)
            raise InvalidTokenError("user credentials revoked")
        return claims

    def get_username(self, token: str) -> str:
        return self.get_claims(token)["sub"]

    def get_authorities(self, token: str) -> List[str]:
        return list(self.get_claims(token).get("auth", []))
