"""Security: JWT tokens, password hashing, user management.

Reference: sitewhere-microservice security/TokenManagement.java (JWT),
service-user-management (users/authorities, BCrypt), JwtServerInterceptor /
TenantTokenServerInterceptor metadata propagation.
"""

from sitewhere_tpu.security.auth import hash_password, verify_password
from sitewhere_tpu.security.tokens import InvalidTokenError, TokenManagement
from sitewhere_tpu.security.users import UserManagement

__all__ = ["InvalidTokenError", "TokenManagement", "UserManagement",
           "hash_password", "verify_password"]
