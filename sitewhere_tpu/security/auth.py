"""Password hashing: salted PBKDF2-HMAC-SHA256.

Replaces the reference's Spring Security BCrypt encoder
(service-user-management persistence; sitewhere-core security/). Format:
``pbkdf2$<iterations>$<salt-hex>$<hash-hex>``.
"""

from __future__ import annotations

import hashlib
import hmac
import os

_ITERATIONS = 100_000


def hash_password(password: str, iterations: int = _ITERATIONS) -> str:
    salt = os.urandom(16)
    digest = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"), salt,
                                 iterations)
    return f"pbkdf2${iterations}${salt.hex()}${digest.hex()}"


def verify_password(password: str, stored: str) -> bool:
    try:
        scheme, iterations_s, salt_hex, hash_hex = stored.split("$")
        if scheme != "pbkdf2":
            return False
        digest = hashlib.pbkdf2_hmac(
            "sha256", password.encode("utf-8"), bytes.fromhex(salt_hex),
            int(iterations_s))
        return hmac.compare_digest(digest.hex(), hash_hex)
    except (ValueError, TypeError):
        return False
