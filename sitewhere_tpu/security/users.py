"""User management: users + granted authorities, authentication.

Reference: service-user-management — IUserManagement CRUD, BCrypt password
checks backing JWT issuance, authority hierarchy
(GrantedAuthorityHierarchy); global (not multitenant) like the reference.

Cluster story: the collection-level mutation feed (`add_mutation_listener`)
is what `multitenant/replication.py` broadcasts to peer hosts; replicated
applies run under `replication()` so stamps adopt the writer's.
`last_login_date` is a PER-HOST observation (recorded quietly, never
emitted) — replicating it would re-stamp the user on every login and let
a login race shadow a concurrent password change under last-writer-wins.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.common import (
    SearchCriteria, SearchResults, now_ms, page)
from sitewhere_tpu.model.user import (
    ACCOUNT_STATUS, GrantedAuthority, SiteWhereRoles, User)
from sitewhere_tpu.registry.store import InMemoryStore, _Collection
from sitewhere_tpu.security.auth import hash_password, verify_password


class UserManagement:
    """IUserManagement: users keyed by username (stored in `token`)."""

    def __init__(self, store=None):
        store = store or InMemoryStore()
        self._replication = threading.local()
        self._mutation_listeners: List[Callable] = []
        self.users: _Collection[User] = _Collection(
            "user", User, store, ErrorCode.INVALID_USERNAME,
            replicating=self._replicating,
            on_mutation=self._emit_mutation)
        self._authorities: Dict[str, GrantedAuthority] = {}
        for role in SiteWhereRoles.ALL:
            self._authorities[role] = GrantedAuthority(
                authority=role, description=role.replace("_", " ").title())

    # -- replication context ----------------------------------------------
    def _replicating(self) -> bool:
        return getattr(self._replication, "active", False)

    @contextmanager
    def replication(self):
        """Mark this thread as applying peer-replicated mutations
        (multitenant/replication.py): creates become idempotent and
        updates adopt the writer's stamp instead of re-touching."""
        prev = getattr(self._replication, "active", False)
        self._replication.active = True
        try:
            yield
        finally:
            self._replication.active = prev

    # -- mutation feed (cluster replication publish side) -----------------
    def add_mutation_listener(self, callback: Callable) -> None:
        """Subscribe to the COMPLETE (kind, op, entity) mutation feed:
        kind "user" for collection mutations, "authority" for granted-
        authority creates."""
        self._mutation_listeners.append(callback)

    def _emit_mutation(self, kind: str, op: str, entity) -> None:
        for callback in list(self._mutation_listeners):
            callback(kind, op, entity)

    # -- users -------------------------------------------------------------
    def create_user(self, user: User, password: str = "") -> User:
        if not user.username:
            raise SiteWhereError("username required", ErrorCode.INVALID_USERNAME)
        if not self._replicating() \
                and not self.users.claimable_replica(user.username) \
                and self.users.get_by_token(user.username) is not None:
            # a claimable replica (peer create arrived first) merges in
            # _Collection.create instead of raising — boot provisioning
            # races stay idempotent cluster-wide
            raise SiteWhereError(f"user '{user.username}' exists",
                                 ErrorCode.DUPLICATE_USER)
        user.token = user.username
        if password:
            user.hashed_password = hash_password(password)
        return self.users.create(user)

    def get_user_by_username(self, username: str) -> Optional[User]:
        return self.users.get_by_token(username)

    def update_user(self, username: str, updates: Dict,
                    password: Optional[str] = None) -> User:
        user = self.users.require_by_token(username)
        if password:
            updates = {**updates, "hashed_password": hash_password(password)}
        return self.users.update(user.id, updates)

    def delete_user(self, username: str) -> User:
        user = self.users.require_by_token(username)
        return self.users.delete(user.id)

    def list_users(self, criteria: Optional[SearchCriteria] = None
                   ) -> SearchResults[User]:
        return self.users.list(criteria)

    # -- authentication ----------------------------------------------------
    def authenticate(self, username: str, password: str,
                     update_last_login: bool = True) -> User:
        """Password check backing JWT issuance (reference
        UserManagementImpl.authenticate)."""
        user = self.users.get_by_token(username)
        if user is None or not verify_password(password, user.hashed_password):
            raise SiteWhereError("invalid credentials",
                                 ErrorCode.INVALID_PASSWORD, http_status=401)
        if user.status != ACCOUNT_STATUS.ACTIVE:
            raise SiteWhereError(f"account {user.status}",
                                 ErrorCode.NOT_AUTHORIZED, http_status=401)
        if update_last_login:
            # quiet per-host observation: no touch(), no mutation emit —
            # a login must not re-stamp the replicated user record
            user.last_login_date = now_ms()
            self.users.persist_quietly(user)
        return user

    # -- authorities -------------------------------------------------------
    def create_granted_authority(self, authority: GrantedAuthority
                                 ) -> GrantedAuthority:
        self._authorities[authority.authority] = authority
        self._emit_mutation("authority", "create", authority)
        return authority

    def get_granted_authority(self, name: str) -> Optional[GrantedAuthority]:
        return self._authorities.get(name)

    def list_granted_authorities(self) -> List[GrantedAuthority]:
        return sorted(self._authorities.values(), key=lambda a: a.authority)

    def get_user_authorities(self, username: str) -> List[str]:
        user = self.users.require_by_token(username)
        return list(user.authorities)
