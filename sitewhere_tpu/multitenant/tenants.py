"""Tenant management: CRUD + model-update notifications.

Reference: service-tenant-management — ITenantManagement CRUD and the
tenant-model-updates Kafka topic (KafkaTopicNaming.java:41) that
MultitenantMicroservices watch to boot/stop tenant engines.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.common import SearchCriteria, SearchResults, new_id
from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.registry.store import InMemoryStore, _Collection


class TenantManagement:
    """ITenantManagement. `bus`/`naming` optional: when present, every
    mutation publishes a tenant-model-update record."""

    def __init__(self, store=None, bus=None, naming=None):
        store = store or InMemoryStore()
        self.tenants: _Collection[Tenant] = _Collection(
            "tenant", Tenant, store, ErrorCode.INVALID_TENANT_TOKEN)
        self.bus = bus
        self.naming = naming

    def _notify(self, operation: str, tenant: Tenant) -> None:
        if self.bus is None or self.naming is None:
            return
        self.bus.publish(
            self.naming.tenant_model_updates(),
            tenant.token.encode(),
            json.dumps({"operation": operation,
                        "tenant": tenant.token}).encode())

    def create_tenant(self, tenant: Tenant) -> Tenant:
        if not tenant.authentication_token:
            tenant.authentication_token = new_id()
        created = self.tenants.create(tenant)
        self._notify("create", created)
        return created

    def get_tenant_by_token(self, token: str) -> Optional[Tenant]:
        return self.tenants.get_by_token(token)

    def get_tenant_by_authentication_token(self, auth_token: str
                                           ) -> Optional[Tenant]:
        for tenant in self.tenants.all():
            if tenant.authentication_token == auth_token:
                return tenant
        return None

    def update_tenant(self, token: str, updates: Dict) -> Tenant:
        entity = self.tenants.require_by_token(token)
        updated = self.tenants.update(entity.id, updates)
        self._notify("update", updated)
        return updated

    def delete_tenant(self, token: str) -> Tenant:
        entity = self.tenants.require_by_token(token)
        deleted = self.tenants.delete(entity.id)
        self._notify("delete", deleted)
        return deleted

    def list_tenants(self, criteria: Optional[SearchCriteria] = None,
                     authorized_user_id: Optional[str] = None
                     ) -> SearchResults[Tenant]:
        if authorized_user_id is None:
            return self.tenants.list(criteria)
        from sitewhere_tpu.model.common import page
        items = [t for t in self.tenants.all()
                 if authorized_user_id in t.authorized_user_ids]
        return page(items, criteria or SearchCriteria())
