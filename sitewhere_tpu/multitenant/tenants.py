"""Tenant management: CRUD + model-update notifications.

Reference: service-tenant-management — ITenantManagement CRUD and the
tenant-model-updates Kafka topic (KafkaTopicNaming.java:41) that
MultitenantMicroservices watch to boot/stop tenant engines.

Cluster story: the collection-level mutation feed (`add_mutation_listener`)
is what `multitenant/replication.py` broadcasts to peer hosts; replicated
applies run under `replication()` so stamps adopt the writer's instead of
re-touching (the registry-gossip contract, registry/store.py).
"""

from __future__ import annotations

import json
import logging
import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.errors import ErrorCode, SiteWhereError
from sitewhere_tpu.model.common import SearchCriteria, SearchResults, new_id
from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.registry.store import InMemoryStore, _Collection
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS

LOGGER = logging.getLogger("sitewhere.tenants")


class TenantManagement:
    """ITenantManagement. `bus`/`naming` optional: when present, every
    mutation publishes a tenant-model-update record."""

    def __init__(self, store=None, bus=None, naming=None):
        store = store or InMemoryStore()
        self._replication = threading.local()
        self._mutation_listeners: List[Callable] = []
        self.tenants: _Collection[Tenant] = _Collection(
            "tenant", Tenant, store, ErrorCode.INVALID_TENANT_TOKEN,
            replicating=self._replicating,
            on_mutation=self._emit_mutation)
        self.bus = bus
        self.naming = naming
        self.notify_dead_lettered = GLOBAL_METRICS.counter(
            "tenants.notify_dead_lettered")

    # -- replication context ----------------------------------------------
    def _replicating(self) -> bool:
        return getattr(self._replication, "active", False)

    @contextmanager
    def replication(self):
        """Mark this thread as applying peer-replicated mutations
        (multitenant/replication.py): creates become idempotent and
        updates adopt the writer's stamp instead of re-touching."""
        prev = getattr(self._replication, "active", False)
        self._replication.active = True
        try:
            yield
        finally:
            self._replication.active = prev

    # -- mutation feed (cluster replication publish side) -----------------
    def add_mutation_listener(self, callback: Callable) -> None:
        """Subscribe to the COMPLETE (kind, op, entity) mutation feed."""
        self._mutation_listeners.append(callback)

    def _emit_mutation(self, kind: str, op: str, entity) -> None:
        for callback in list(self._mutation_listeners):
            callback(kind, op, entity)

    def _notify(self, operation: str, tenant: Tenant) -> None:
        if self.bus is None or self.naming is None:
            return
        topic = self.naming.tenant_model_updates()
        key = tenant.token.encode()
        value = json.dumps({"operation": operation,
                            "tenant": tenant.token}).encode()
        try:
            self.bus.publish(topic, key, value)
        except Exception:
            # The store mutation already committed: raising here would
            # desync store vs. topic (the caller would see a failure for a
            # write that happened). Park the notification on the
            # dead-letter topic for operator replay instead, and count it.
            self.notify_dead_lettered.inc()
            LOGGER.exception(
                "tenant-model-update publish failed for %s %r — parked on "
                "%s.dead-letter", operation, tenant.token, topic)
            try:
                self.bus.publish(f"{topic}.dead-letter", key, value)
            except Exception:
                LOGGER.exception("dead-letter parking failed too; "
                                 "notification for %s %r lost",
                                 operation, tenant.token)

    def create_tenant(self, tenant: Tenant) -> Tenant:
        if not tenant.authentication_token:
            tenant.authentication_token = new_id()
        created = self.tenants.create(tenant)
        self._notify("create", created)
        return created

    def get_tenant_by_token(self, token: str) -> Optional[Tenant]:
        return self.tenants.get_by_token(token)

    def get_tenant_by_authentication_token(self, auth_token: str
                                           ) -> Optional[Tenant]:
        for tenant in self.tenants.all():
            if tenant.authentication_token == auth_token:
                return tenant
        return None

    def update_tenant(self, token: str, updates: Dict) -> Tenant:
        entity = self.tenants.require_by_token(token)
        updated = self.tenants.update(entity.id, updates)
        self._notify("update", updated)
        return updated

    def delete_tenant(self, token: str) -> Tenant:
        entity = self.tenants.require_by_token(token)
        deleted = self.tenants.delete(entity.id)
        self._notify("delete", deleted)
        return deleted

    def list_tenants(self, criteria: Optional[SearchCriteria] = None,
                     authorized_user_id: Optional[str] = None
                     ) -> SearchResults[Tenant]:
        if authorized_user_id is None:
            return self.tenants.list(criteria)
        from sitewhere_tpu.model.common import page
        items = [t for t in self.tenants.all()
                 if authorized_user_id in t.authorized_user_ids]
        return page(items, criteria or SearchCriteria())
