"""Multitenancy: tenant CRUD, per-tenant engines, instance bootstrap.

Reference: service-tenant-management, MultitenantMicroservice.java:54,
MicroserviceTenantEngine, service-instance-management.
"""

from sitewhere_tpu.multitenant.tenants import TenantManagement
from sitewhere_tpu.multitenant.engine import TenantEngine, TenantEngineManager
from sitewhere_tpu.multitenant.instance import (
    InstanceBootstrap, TenantTemplate, builtin_templates)

__all__ = ["InstanceBootstrap", "TenantEngine", "TenantEngineManager",
           "TenantManagement", "TenantTemplate", "builtin_templates"]
