"""Instance bootstrap: templates + initializers.

Reference: service-instance-management — InstanceTemplateManager.java:32
copies instance templates (user + tenant init scripts) into ZooKeeper and
runs GroovyUserModelInitializer / GroovyTenantModelInitializer. Here a
template is declarative data plus optional Python initializer callables (the
Groovy extension point without a JVM), applied directly to the managements.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.model.area import Area, AreaType, Zone
from sitewhere_tpu.model.common import Location
from sitewhere_tpu.model.device import Device, DeviceAssignment, DeviceType
from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.model.user import SiteWhereRoles, User

LOGGER = logging.getLogger("sitewhere.instance")


@dataclass
class TenantTemplate:
    """Declarative tenant bootstrap dataset (the reference's tenant
    templates: 'empty', 'construction', ... with Groovy initializers)."""

    template_id: str
    name: str = ""
    device_types: List[DeviceType] = field(default_factory=list)
    area_types: List[AreaType] = field(default_factory=list)
    areas: List[Area] = field(default_factory=list)
    zones: List[Zone] = field(default_factory=list)  # area token in area_id
    devices: List[Device] = field(default_factory=list)  # type token in device_type_id
    assign_all: bool = False  # auto-assign created devices
    initializers: List[Callable] = field(default_factory=list)  # (engine) -> None

    def apply(self, engine) -> None:
        """Materialize the dataset into a TenantEngine's registries.

        Entities are deep-copied and re-identified per tenant — a template is
        shared across every tenant that bootstraps from it, so handing the
        same instances to two registries would alias mutable state across
        tenants."""
        import copy

        from sitewhere_tpu.model.common import new_id

        def fresh(entity):
            clone = copy.deepcopy(entity)
            clone.id = new_id()
            return clone

        registry = engine.registry
        for area_type in self.area_types:
            registry.create_area_type(fresh(area_type))
        area_ids: Dict[str, str] = {}
        for area in self.areas:
            created = registry.create_area(fresh(area))
            area_ids[created.token] = created.id
        for zone in self.zones:
            clone = fresh(zone)
            if clone.area_id in area_ids:  # token -> id
                clone.area_id = area_ids[clone.area_id]
            registry.create_zone(clone)
        type_ids: Dict[str, str] = {}
        for device_type in self.device_types:
            created = registry.create_device_type(fresh(device_type))
            type_ids[created.token] = created.id
        for device in self.devices:
            clone = fresh(device)
            if clone.device_type_id in type_ids:  # token -> id
                clone.device_type_id = type_ids[clone.device_type_id]
            created = registry.create_device(clone)
            if self.assign_all:
                registry.create_device_assignment(
                    DeviceAssignment(device_id=created.id))
        for initializer in self.initializers:
            initializer(engine)


def builtin_templates() -> Dict[str, TenantTemplate]:
    """'empty' + a small demo dataset (the reference ships template-empty
    and template-construction)."""
    demo = TenantTemplate(
        template_id="demo", name="Demo dataset",
        device_types=[DeviceType(token="gateway", name="Gateway"),
                      DeviceType(token="sensor", name="Sensor")],
        areas=[Area(token="site-1", name="Site 1")],
        zones=[Zone(token="perimeter", area_id="site-1", bounds=[
            Location(0.0, 0.0), Location(0.0, 1.0), Location(1.0, 1.0),
            Location(1.0, 0.0)])],
        devices=[Device(token=f"demo-{i}", device_type_id="sensor")
                 for i in range(4)],
        assign_all=True)
    return {
        "empty": TenantTemplate(template_id="empty", name="Empty"),
        "demo": demo,
    }


class InstanceBootstrap:
    """Instance-level bring-up (InstanceTemplateManager + user/tenant model
    initializers): default admin user + default tenant, then template
    application whenever an engine boots."""

    def __init__(self, user_management, tenant_management,
                 templates: Optional[Dict[str, TenantTemplate]] = None,
                 admin_username: str = "admin",
                 admin_password: str = "password"):
        self.users = user_management
        self.tenants = tenant_management
        self.templates = templates or builtin_templates()
        self.admin_username = admin_username
        self.admin_password = admin_password

    def bootstrap_users(self) -> None:
        if self.users.get_user_by_username(self.admin_username) is None:
            self.users.create_user(
                User(username=self.admin_username, first_name="Admin",
                     authorities=list(SiteWhereRoles.ALL)),
                password=self.admin_password)

    def bootstrap_default_tenant(self, token: str = "default",
                                 template_id: str = "empty") -> Tenant:
        tenant = self.tenants.get_tenant_by_token(token)
        if tenant is None:
            # deterministic authentication token: every cluster host
            # bootstraps this tenant independently, and identical content
            # means the replicated creates converge as no-ops instead of
            # LWW-merging a random per-host token (which would restart
            # the engine on every losing host at boot)
            tenant = self.tenants.create_tenant(Tenant(
                token=token, name=token.title(),
                authentication_token=f"{token}-auth",
                tenant_template_id=template_id))
        return tenant

    def apply_template(self, engine) -> None:
        """Run on tenant-engine boot (tenantInitialize in the reference)."""
        template = self.templates.get(engine.tenant.tenant_template_id)
        if template is None:
            LOGGER.warning("unknown tenant template '%s'",
                           engine.tenant.tenant_template_id)
            return
        template.apply(engine)
