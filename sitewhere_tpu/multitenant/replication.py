"""Cluster-wide control-plane provisioning: tenant/user/authority
replication with reactive engine lifecycle.

Reference: the tenant-model-updates topic (KafkaTopicNaming.java:41) that
every MultitenantMicroservice watches to boot/stop tenant engines
reactively (MultitenantMicroservice.java:64-70,:238), plus the shared
user store every service authenticates against. The dispatcher-less SPMD
cluster (parallel/cluster.py) replicates the registry via leaderless
gossip but — until this module — tenant/user provisioning rode identical
boot templates: a tenant created over REST on host A did not exist on B.

This module closes that gap with the same replication algebra the
registry gossip uses (and the gossip now imports ITS core from here —
one LWW + tombstone + content-digest implementation, two consumers):

- **Publish side** — `TenantManagement` / `UserManagement` mutations
  (complete collection-level feeds, so no wrapper can forget to
  replicate) are stamped (explicit `updated_date`, resurrection bumps
  past known tombstones, deletes stamp past the entity's last write)
  and broadcast to every peer's bus edge. A peer publish failure parks
  the payload on the local dead-letter topic for operator replay.
- **Apply side** — idempotent last-writer-wins: the stamp orders
  writers, a host-independent content digest breaks exact ties, and
  tombstones make deletes beat stale creates while a NEWER write
  resurrects. Applies run through the regular management surface under
  its `replication()` context, so the store mutation also publishes the
  LOCAL `tenant-model-updates` record — which is exactly what makes the
  applier *reactive*: the TenantEngineManager watching that topic boots
  the tenant engine (registering its registry with the cluster gossip)
  on a replicated `create`, restarts it on `update`, and retires it on
  `delete`. A tenant delete additionally parks the tenant's in-flight
  decoded-event rows on the dead-letter topic instead of dropping them,
  and user mutations invalidate the JWT auth-state cache
  (`security/tokens.py`) — a deleted user's tokens are rejected
  cluster-wide.
- **Durability** — `export_provisioning` / `apply_provisioning` carry
  the whole provisioning state (plus tombstones) inside the instance
  checkpoint manifest, so a gang restart rebuilds the same tenant set
  from durable state, not boot templates.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
from typing import Dict, List, Optional, Tuple

import msgpack

from sitewhere_tpu.errors import (
    DuplicateTokenError, ErrorCode, NotFoundError, SiteWhereError)
from sitewhere_tpu.model.common import now_ms
from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.model.user import GrantedAuthority, User
from sitewhere_tpu.runtime.bus import ConsumerHost, Record, TopicNaming
from sitewhere_tpu.runtime.metrics import GLOBAL_METRICS
from sitewhere_tpu.runtime.recovery import EpochFence

LOGGER = logging.getLogger("sitewhere.provisioning")

PROVISIONING_SUFFIX = "provisioning-model-updates"

# per-kind PER-HOST observation fields: excluded from LWW diffs and the
# content digest the same way created_date is (a host's own login
# bookkeeping must not churn replicated content)
_OBSERVED_FIELDS = {"user": ("last_login_date",)}

_MODEL_CLASSES = {"tenant": Tenant, "user": User}


def provisioning_topic(naming: TopicNaming) -> str:
    return naming.provisioning_model_updates()


# ---------------------------------------------------------------------------
# LWW + content-digest core (shared with parallel/cluster.py RegistryGossip)
# ---------------------------------------------------------------------------

def lww_stamp(data: Dict) -> int:
    """Last-writer-wins timestamp of a serialized entity."""
    return int(data.get("updated_date") or data.get("created_date") or 0)


def content_digest(data: Dict,
                   ref_tokens: Optional[Dict[str, str]] = None,
                   drop_fields: Tuple[str, ...] = ()) -> str:
    """Deterministic tiebreak for equal-stamp concurrent writes: a digest
    over the entity's HOST-INDEPENDENT content — per-host UUID ids and
    per-host observations (`created_date`, `drop_fields`) are dropped,
    replicated references appear by token, and `updated_date` normalizes
    to the LWW stamp — so every host hashing its local copy and the
    incoming copy computes the same pair of keys and picks the same
    winner."""
    content = {k: v for k, v in data.items()
               if k not in ("id", "created_date") and k not in drop_fields}
    content["updated_date"] = lww_stamp(data)
    content["_refs"] = dict(sorted((ref_tokens or {}).items()))
    blob = json.dumps(content, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()


def _digest(kind: str, data: Dict) -> str:
    return content_digest(data, drop_fields=_OBSERVED_FIELDS.get(kind, ()))


# ---------------------------------------------------------------------------
# checkpoint payload (gang-restart durability)
# ---------------------------------------------------------------------------

def export_provisioning(instance) -> Dict:
    """Whole-state provisioning snapshot for the instance checkpoint
    manifest: tenants + users + authorities, plus the replicator's known
    tombstones (a replayed stale create must stay dead after restart)."""
    from sitewhere_tpu.web.marshal import to_jsonable

    replicator = replicator_of(instance)
    return {
        "tenants": [to_jsonable(t)
                    for t in instance.tenant_management.tenants.all()],
        "users": [to_jsonable(u)
                  for u in instance.user_management.users.all()],
        "authorities": [to_jsonable(a) for a in
                        instance.user_management.list_granted_authorities()],
        "tombstones": ([[k, t, s] for (k, t), s in
                        sorted(replicator._tombstones.items())]
                       if replicator is not None else []),
    }


def apply_provisioning(instance, state: Optional[Dict]) -> int:
    """Merge a checkpointed provisioning snapshot into the live
    managements, last-writer-wins (local durable stores may be newer).
    Runs at boot restore BEFORE the tenant engine manager starts, so the
    restored tenant set — not the boot templates — decides which engines
    boot. Returns the number of applied records."""
    if not state:
        return 0
    replicator = replicator_of(instance)
    tombstones: Dict[Tuple[str, str], int] = {}
    for kind, token, stamp in state.get("tombstones", []):
        tombstones[(str(kind), str(token))] = int(stamp)
        if replicator is not None:
            key = (str(kind), str(token))
            replicator._tombstones[key] = max(
                replicator._tombstones.get(key, 0), int(stamp))
    applied = 0
    for data in state.get("tenants", []):
        tomb = tombstones.get(("tenant", data.get("token", "")))
        if tomb is not None and lww_stamp(data) <= tomb:
            continue
        applied += _apply_entity(instance, "tenant", dict(data))
    for data in state.get("users", []):
        tomb = tombstones.get(("user", data.get("token", "")))
        if tomb is not None and lww_stamp(data) <= tomb:
            continue
        applied += _apply_entity(instance, "user", dict(data))
    users = instance.user_management
    for data in state.get("authorities", []):
        name = data.get("authority", "")
        if name and users.get_granted_authority(name) is None:
            users.create_granted_authority(
                GrantedAuthority(**{k: data[k] for k in
                                    ("authority", "description", "parent",
                                     "group") if k in data}))
            applied += 1
    return applied


def replicator_of(instance):
    replicator = getattr(instance, "provisioning_replicator", None)
    if replicator is not None:
        return replicator
    hooks = getattr(instance, "cluster_hooks", None)
    return getattr(hooks, "provisioning", None) if hooks is not None else None


def _apply_entity(instance, kind: str, entity_data: Dict) -> int:
    """Idempotent LWW create-or-update of one tenant/user record through
    the management surface (shared by the gossip applier and the
    checkpoint restore). Returns 1 when local state changed."""
    from sitewhere_tpu.web.marshal import entity_from_payload, to_jsonable

    token = entity_data.get("token", "")
    if not token:
        return 0
    mgmt = (instance.tenant_management if kind == "tenant"
            else instance.user_management)
    coll = mgmt.tenants if kind == "tenant" else mgmt.users
    existing = coll.get_by_token(token)
    if existing is None:
        entity = entity_from_payload(_MODEL_CLASSES[kind], entity_data)
        try:
            with mgmt.replication():
                if kind == "tenant":
                    mgmt.create_tenant(entity)
                else:
                    coll.create(entity)
        except DuplicateTokenError:
            pass  # raced another replica of the same create
        return 1
    # LWW: stamps first, host-independent digest on exact ties
    import dataclasses as _dc

    current = to_jsonable(existing)
    inc_ts, loc_ts = lww_stamp(entity_data), lww_stamp(current)
    if inc_ts < loc_ts:
        return 0  # stale: the local copy already won
    if inc_ts == loc_ts and _digest(kind, entity_data) <= _digest(kind,
                                                                  current):
        return 0  # identical, or the local copy wins the tiebreak
    coerced = entity_from_payload(type(existing), entity_data)
    inc_json = to_jsonable(coerced)
    fields = ({f.name for f in _dc.fields(type(existing))}
              - {"id", "token", "created_date"}
              - set(_OBSERVED_FIELDS.get(kind, ())))
    diff = {name: getattr(coerced, name) for name in fields
            if current.get(name) != inc_json.get(name)}
    if not diff:
        return 0
    with mgmt.replication():
        if kind == "tenant":
            # fires the local tenant-model-updates record too -> the
            # engine manager restarts the live engine (reactive update)
            mgmt.update_tenant(token, diff)
        else:
            mgmt.update_user(token, diff)
    return 1


# ---------------------------------------------------------------------------
# the replicator
# ---------------------------------------------------------------------------

class ProvisioningReplicator:
    """Leaderless cross-host tenant/user/authority replication
    (module docstring). Construct with the instance BEFORE
    `instance.start()` so the bootstrap mutations replicate too; `start()`
    after the instance is up (the ConsumerHost applies peer records)."""

    def __init__(self, process_id: int, peers: Dict[int, object],
                 instance, naming: TopicNaming):
        self.process_id = process_id
        self.peers = peers
        self.instance = instance
        self.topic = provisioning_topic(naming)
        self.published = 0
        self.applied = 0
        self.conflicts = 0
        self.publish_errors = 0
        self.parked_rows = 0
        # recovery-epoch fencing (runtime/recovery.py): every envelope is
        # stamped with this host's origin identity + current epoch, and
        # the apply side keeps per-origin floors — a fenced (taken-over)
        # peer's stale envelopes are rejected instead of resurrecting
        # pre-takeover provisioning state. Epochs only compare within one
        # origin; envelopes without a stamp (older peers) always admit.
        self.origin = f"proc:{process_id}"
        self.epoch = 0
        self._fence = EpochFence()
        self._applying = threading.local()
        # (kind, token) -> delete stamp; seeded from the checkpoint at
        # boot restore (apply_provisioning) so replayed stale creates
        # stay dead across gang restarts
        self._tombstones: Dict[Tuple[str, str], int] = {}
        self._host = ConsumerHost(
            instance.bus, self.topic,
            group_id=f"provisioning-replication-{process_id}",
            handler=self._handle)
        instance.tenant_management.add_mutation_listener(
            lambda kind, op, entity: self._on_mutation("tenant", op, entity))
        instance.user_management.add_mutation_listener(self._on_user_mutation)
        # discoverable from the instance (checkpoint export, REST status)
        # even before/without cluster hooks installation
        instance.provisioning_replicator = self

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._host.start()

    def stop(self) -> None:
        self._host.stop()

    # -- publish side ------------------------------------------------------
    def _on_user_mutation(self, kind: str, op: str, entity) -> None:
        # kind is "user" (collection feed) or "authority" (explicit emit)
        self._on_mutation(kind, op, entity)

    def _on_mutation(self, kind: str, op: str, entity) -> None:
        if getattr(self._applying, "active", False):
            return  # echo of an applied peer mutation
        if not self.peers:
            return
        from sitewhere_tpu.web.marshal import to_jsonable

        token = getattr(entity, "token", "") or getattr(
            entity, "authority", "")
        try:
            if op == "delete":
                data = to_jsonable(entity)
                stamp = max(now_ms(), lww_stamp(data) + 1)
                # the deleting host never consumes its own publish:
                # record the tombstone HERE too, or an in-flight
                # concurrent peer update would resurrect locally
                key = (kind, token)
                self._tombstones[key] = max(self._tombstones.get(key, 0),
                                            stamp)
                payload = self._envelope(
                    {"kind": kind, "op": "delete", "token": token,
                     "stamp": stamp})
                if kind == "tenant":
                    # the local host parks its own in-flight rows; each
                    # peer parks its own on apply
                    self._park_inflight(token)
            elif kind == "authority":
                payload = self._envelope(
                    {"kind": kind, "op": op, "entity": to_jsonable(entity),
                     "stamp": now_ms()})
            else:
                self._stamp_live_entity(kind, entity)
                payload = self._envelope(
                    {"kind": kind, "op": op,
                     "entity": to_jsonable(entity)})
        except Exception:
            LOGGER.exception("provisioning encode failed (%s %s)", kind, op)
            return
        self._publish(f"{kind}:{token}".encode(), payload)

    def _stamp_live_entity(self, kind: str, entity) -> None:
        """Make the LWW stamp explicit on the live entity (a create's
        stamp implicitly rides created_date, which deliberately does not
        converge), and bump a resurrection past any known tombstone so
        every replica compares the same winning pair."""
        from sitewhere_tpu.web.marshal import to_jsonable

        if entity.updated_date is None:
            entity.updated_date = entity.created_date
        tomb = self._tombstones.get((kind, entity.token))
        if tomb is not None and lww_stamp(to_jsonable(entity)) <= tomb:
            entity.updated_date = tomb + 1
            coll = (self.instance.tenant_management.tenants
                    if kind == "tenant"
                    else self.instance.user_management.users)
            try:
                # the row was already saved before this listener fired:
                # persist the bumped stamp too (no re-emit)
                coll.persist_quietly(entity)
            except Exception:
                LOGGER.exception("could not persist resurrection stamp "
                                 "for %s %r", kind, entity.token)

    def _envelope(self, body: Dict) -> bytes:
        body["origin"] = self.origin
        body["epoch"] = int(self.epoch)
        return msgpack.packb(body, use_bin_type=True)

    def set_epoch(self, epoch: int) -> None:
        """Adopt the instance's minted recovery epoch (instance boot /
        takeover re-mint); outgoing envelopes carry it from here on."""
        self.epoch = int(epoch)

    def fence(self, origin: str, epoch: int) -> int:
        """Raise the apply-side floor for `origin` (takeover broadcast):
        envelopes it stamped below `epoch` are rejected from now on."""
        return self._fence.fence(str(origin), int(epoch))

    def _publish(self, key: bytes, payload: bytes) -> None:
        from sitewhere_tpu.runtime.busnet import BusNetError

        for pid, client in self.peers.items():
            try:
                client.publish(self.topic, key, payload)
                self.published += 1
            except BusNetError:
                self.publish_errors += 1
                # park for operator replay toward the peer
                self.instance.bus.publish(f"{self.topic}.dead-letter",
                                          key, payload)

    # -- apply side --------------------------------------------------------
    def _handle(self, records: List[Record]) -> None:
        self._applying.active = True
        try:
            for record in records:
                try:
                    data = msgpack.unpackb(record.value, raw=False)
                except Exception:
                    continue
                try:
                    self._apply(dict(data))
                except SiteWhereError:
                    self.conflicts += 1
                    raise  # retry budget -> dead-letter surface
        finally:
            self._applying.active = False

    def _apply(self, data: Dict) -> None:
        origin = data.get("origin")
        if origin is not None and not self._fence.admit(
                str(origin), int(data.get("epoch", 0))):
            # stale-epoch envelope from a fenced (taken-over) writer:
            # admit() already counted it on `fencing.rejected`
            LOGGER.warning(
                "rejected stale provisioning envelope from %s "
                "(epoch %s < floor %d)", origin, data.get("epoch"),
                self._fence.floor(str(origin)))
            return
        kind = data.get("kind")
        if kind == "authority":
            self._apply_authority(data)
            return
        if kind not in _MODEL_CLASSES:
            return
        if data.get("op") == "delete":
            self._apply_delete(kind, data)
            return
        entity_data = dict(data.get("entity") or {})
        token = entity_data.get("token", "")
        tomb = self._tombstones.get((kind, token))
        if tomb is not None and lww_stamp(entity_data) <= tomb:
            return  # a write that lost to an applied deletion stays dead
        if _apply_entity(self.instance, kind, entity_data):
            self.applied += 1

    def _apply_delete(self, kind: str, data: Dict) -> None:
        from sitewhere_tpu.web.marshal import to_jsonable

        token = data.get("token", "")
        stamp = int(data.get("stamp") or 0)
        key = (kind, token)
        self._tombstones[key] = max(self._tombstones.get(key, 0), stamp)
        mgmt = (self.instance.tenant_management if kind == "tenant"
                else self.instance.user_management)
        coll = mgmt.tenants if kind == "tenant" else mgmt.users
        existing = coll.get_by_token(token)
        if existing is None:
            return  # idempotent redelivery, or the entity never arrived
        if lww_stamp(to_jsonable(existing)) > stamp:
            return  # a concurrent write outranked the delete: keep it
        try:
            if kind == "tenant":
                # reactive: drain + retire the engine FIRST so its
                # consumers stop pulling, then delete (which also fires
                # the local tenant-model-updates delete record)
                self.instance.engine_manager.retire_engine(token)
                with mgmt.replication():
                    mgmt.delete_tenant(token)
                self._park_inflight(token)
            else:
                with mgmt.replication():
                    mgmt.delete_user(token)
        except NotFoundError:
            return
        self.applied += 1

    def _apply_authority(self, data: Dict) -> None:
        users = self.instance.user_management
        entity = dict(data.get("entity") or {})
        name = entity.get("authority", "")
        if not name or users.get_granted_authority(name) is not None:
            return
        users.create_granted_authority(GrantedAuthority(
            **{k: entity[k] for k in ("authority", "description", "parent",
                                      "group") if k in entity}))
        self.applied += 1

    # -- tenant-delete drain ----------------------------------------------
    def _park_inflight(self, tenant_token: str) -> None:
        """Rows already published for the deleted tenant but not yet
        consumed park on the dead-letter topic instead of silently dying
        with the topic (the engine is already stopped, so its consumer
        group is not competing for the cursor)."""
        bus = self.instance.bus
        naming = self.instance.naming
        topic = naming.event_source_decoded_events(tenant_token)
        consumer = bus.consumer(topic, f"inbound-processing-{tenant_token}")
        parked = 0
        while True:
            batch = consumer.poll(4096)
            if not batch:
                break
            bus.topic(f"{topic}.dead-letter").publish_many(
                [(r.key, r.value) for r in batch])
            bus.commit(consumer)
            parked += len(batch)
        if parked:
            self.parked_rows += parked
            GLOBAL_METRICS.counter("provisioning.parked_rows").inc(parked)
            LOGGER.warning("tenant %r deleted with %d in-flight rows — "
                           "parked on %s.dead-letter", tenant_token, parked,
                           topic)

    # -- status ------------------------------------------------------------
    def status(self) -> Dict:
        return {
            "mode": "replicated",
            "peers": len(self.peers),
            "published": self.published,
            "applied": self.applied,
            "conflicts": self.conflicts,
            "publishErrors": self.publish_errors,
            "parkedRows": self.parked_rows,
            "tombstones": len(self._tombstones),
            "origin": self.origin,
            "epoch": self.epoch,
            "fencedOrigins": self._fence.snapshot(),
        }
