"""Per-tenant engine: the full service stack for one tenant, one component
tree.

Reference: MultitenantMicroservice.java:54 keeps a map of tenant ->
MicroserviceTenantEngine (:64-70), boots engines for existing tenants on
start (:238), restarts failed engines (:284-303), and reacts to
tenant-model-updates. In the reference each of ~15 services runs its own
tenant engine; here ONE TenantEngine wires the whole per-tenant pipeline
(registry -> event management -> inbound -> enrichment -> delivery/
registration/connectors/rules/schedule/batch) around the SHARED process-wide
TPU pipeline engine + columnar log — the microservice fan-out collapsed into
a component tree (SURVEY.md §2.5: SPMD replaces RPC fan-out).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.assets import AssetManagement
from sitewhere_tpu.batch import (
    BatchCommandInvocationHandler, BatchManagement, BatchOperationManager)
from sitewhere_tpu.commands import CommandDeliveryService
from sitewhere_tpu.connectors import OutboundConnectorsManager
from sitewhere_tpu.model.batch import BatchOperationTypes
from sitewhere_tpu.model.schedule import ScheduledJobType
from sitewhere_tpu.model.tenant import Tenant
from sitewhere_tpu.persist.event_management import (
    DeviceEventManagement, EventPersistenceTriggers)
from sitewhere_tpu.pipeline.enrichment import PayloadEnrichment
from sitewhere_tpu.pipeline.inbound import InboundProcessingService
from sitewhere_tpu.registration import RegistrationManager
from sitewhere_tpu.registry.store import DeviceManagement
from sitewhere_tpu.rules import RuleProcessorsManager
from sitewhere_tpu.runtime.bus import ConsumerHost, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.schedule import (
    BatchCommandInvocationJobExecutor, CommandInvocationJobExecutor,
    ScheduleManagement, ScheduleManager)
from sitewhere_tpu.search import (ColumnarSearchProvider,
                                  SearchProvidersManager)
from sitewhere_tpu.sources.manager import EventSourcesManager
from sitewhere_tpu.streams import DeviceStreamManager

LOGGER = logging.getLogger("sitewhere.tenant")


class TenantEngine(LifecycleComponent):
    """Everything one tenant needs, assembled + lifecycle-managed.

    Shared process-level pieces come in as arguments (bus, columnar log,
    pipeline engine, registry tensors); per-tenant stores are created here.
    """

    def __init__(self, tenant: Tenant, bus, log, pipeline_engine=None,
                 registry_tensors=None, store_factory: Optional[Callable] = None,
                 naming: Optional[TopicNaming] = None, cluster=None,
                 batcher=None):
        super().__init__(f"tenant-engine:{tenant.token}")
        self.tenant = tenant
        self.tenant_id = tenant.token
        self.bus = bus
        self.log = log
        self.naming = naming or TopicNaming()
        self.pipeline_engine = pipeline_engine

        make_store = store_factory or (lambda kind: None)

        # registries
        self.registry = DeviceManagement(make_store("registry"), tenant.token)
        self.asset_management = AssetManagement(make_store("assets"),
                                                tenant.token)
        if registry_tensors is not None:
            registry_tensors.attach(self.registry, tenant.token)
        if cluster is not None and hasattr(cluster, "gossip") \
                and cluster.gossip is not None:
            # cross-host registry replication: this tenant's mutations
            # broadcast to peers; theirs apply here (cluster.py)
            cluster.gossip.register_tenant_registry(tenant.token,
                                                    self.registry)

        # event persistence + triggers. The pipeline packer's device
        # interner rides along so control-plane appends (inbound persist,
        # REST event posts, persisted rule alerts) stamp the SAME positive
        # device_idx the hot path does — lookup() never allocates, so an
        # unregistered token still lands as idx 0 (UNKNOWN) and the
        # serving tier's window cache falls back to the monolithic scan
        # for ranges containing it (serving/wincache.py). Without this
        # every REST-ingested row was idx 0 and the cache never engaged.
        self.event_management = DeviceEventManagement(
            log, self.registry, tenant.token,
            device_interner=(pipeline_engine.packer.devices
                             if pipeline_engine is not None else None))
        EventPersistenceTriggers(bus, self.naming,
                                 tenant.token).attach(self.event_management)

        # pipeline services (cluster hooks route foreign-owned records to
        # their owner host and feed the lockstep step loop — cluster.py).
        # A control-plane-only cluster (data_plane=False: registry +
        # provisioning replicate, but each host runs its own engine and
        # owns every device locally) does not participate in ownership
        # routing, so inbound keeps the direct single-host submit path.
        inbound_cluster = (cluster if cluster is not None
                           and getattr(cluster, "data_plane", True)
                           else None)
        self.inbound = InboundProcessingService(
            bus, self.registry, events=self.event_management,
            engine=pipeline_engine, tenant=tenant.token, naming=self.naming,
            cluster=inbound_cluster, batcher=batcher)
        self.enrichment = PayloadEnrichment(bus, self.registry, tenant.token,
                                            self.naming)
        self.command_delivery = CommandDeliveryService(
            bus, self.registry, tenant.token, self.naming)
        self.registration = RegistrationManager(
            bus, self.registry, tenant.token, self.naming,
            command_delivery=self.command_delivery)
        self.event_sources = EventSourcesManager()
        self.connectors = OutboundConnectorsManager(bus, tenant.token,
                                                    self.naming)
        self.rule_processors = RuleProcessorsManager(bus, tenant.token,
                                                     self.naming)

        # streaming media + federated search
        self.streams = DeviceStreamManager(self.registry,
                                           self.event_management,
                                           store=make_store("streams"))
        self.search_providers = SearchProvidersManager()
        self.search_providers.register(
            ColumnarSearchProvider(log, tenant.token))

        # batch + schedule
        self.batch_management = BatchManagement(make_store("batch"))
        self.batch_manager = BatchOperationManager(self.batch_management)
        self.batch_manager.register_handler(
            BatchOperationTypes.INVOKE_COMMAND,
            BatchCommandInvocationHandler(self.registry,
                                          self.event_management))
        self.schedule_management = ScheduleManagement(make_store("schedule"))
        self.schedule_manager = ScheduleManager(self.schedule_management)
        self.schedule_manager.register_executor(
            ScheduledJobType.COMMAND_INVOCATION,
            CommandInvocationJobExecutor(self.registry, self.event_management))
        self.schedule_manager.register_executor(
            ScheduledJobType.BATCH_COMMAND_INVOCATION,
            BatchCommandInvocationJobExecutor(
                self.registry, self.batch_manager, self.batch_management))
        if pipeline_engine is not None and \
                hasattr(pipeline_engine, "anomaly_model_manifest"):
            # unattended drift-refit sweeps (PR 19 follow-up): a
            # DRIFT_REFIT job walks installed anomaly models and pushes
            # refits through the gossip-replicated upsert path
            from sitewhere_tpu.actuation.refit import (
                DriftRefitJobExecutor, DriftRefitter)
            self.drift_refitter = DriftRefitter(pipeline_engine)
            self.schedule_manager.register_executor(
                ScheduledJobType.DRIFT_REFIT,
                DriftRefitJobExecutor(self.drift_refitter))
        else:
            self.drift_refitter = None

        for component in (self.event_management, self.inbound, self.enrichment,
                          self.command_delivery, self.registration,
                          self.event_sources, self.connectors,
                          self.rule_processors, self.batch_manager,
                          self.schedule_manager, self.streams,
                          self.search_providers):
            self.add_nested(component)


class TenantEngineManager(LifecycleComponent):
    """tenant -> engine map with boot/restart semantics
    (MultitenantMicroservice.java:64-70, restart :284-303). Watches
    tenant-model-updates to add/remove engines live."""

    def __init__(self, tenant_management, engine_factory: Callable[[Tenant],
                                                                   TenantEngine],
                 bus=None, naming: Optional[TopicNaming] = None):
        super().__init__("tenant-engine-manager")
        self.tenant_management = tenant_management
        self.engine_factory = engine_factory
        self.bus = bus
        self.naming = naming or TopicNaming()
        self.engines: Dict[str, TenantEngine] = {}
        self.failed: Dict[str, str] = {}  # token -> error
        self._starting: set = set()  # tokens mid-boot (start_engine guard)
        self._stopped: set = set()   # tokens explicitly stopped by an admin
        self._lock = threading.RLock()
        self._watch: Optional[ConsumerHost] = None

    # -- lifecycle ---------------------------------------------------------
    def on_start(self, monitor) -> None:
        for tenant in self.tenant_management.tenants.all():
            self.start_engine(tenant.token)
        if self.bus is not None:
            self._watch = ConsumerHost(
                self.bus, self.naming.tenant_model_updates(),
                group_id="tenant-engine-manager", handler=self._on_updates)
            self._watch.start()

    def on_stop(self, monitor) -> None:
        if self._watch is not None:
            self._watch.stop()
            self._watch = None
        with self._lock:
            engines = list(self.engines.values())
            self.engines.clear()
        for engine in engines:
            try:
                engine.stop()
            except Exception:
                LOGGER.exception("stopping tenant engine %s failed",
                                 engine.tenant.token)

    # -- engine control ----------------------------------------------------
    def get_engine(self, tenant_token: str) -> Optional[TenantEngine]:
        with self._lock:
            return self.engines.get(tenant_token)

    def is_stopped(self, tenant_token: str) -> bool:
        """True when an admin explicitly stopped this engine (it must not be
        auto-restarted by lazy request-path resolution)."""
        with self._lock:
            return tenant_token in self._stopped

    def start_engine(self, tenant_token: str, wait_seconds: float = 30.0,
                     force: bool = False) -> Optional[TenantEngine]:
        """Boot (or return) the engine. A non-forced start respects an
        explicit admin stop — only `force=True` (the admin start/restart
        endpoints) clears the stopped flag, so stale async model-update
        records can't resurrect a stopped engine."""
        import time as _time
        deadline = _time.monotonic() + wait_seconds
        while True:
            with self._lock:
                if force:
                    self._stopped.discard(tenant_token)
                elif tenant_token in self._stopped:
                    return None
                if tenant_token in self.engines:
                    return self.engines[tenant_token]
                if tenant_token not in self._starting:
                    self._starting.add(tenant_token)
                    break
            # another thread is booting this tenant — wait for it rather
            # than surfacing a spurious "unknown tenant" to the caller
            if _time.monotonic() > deadline:
                return None
            _time.sleep(0.02)
        try:
            tenant = self.tenant_management.get_tenant_by_token(tenant_token)
            if tenant is None:
                return None
            try:
                engine = self.engine_factory(tenant)
                engine.start()
            except Exception as exc:
                with self._lock:
                    self.failed[tenant_token] = str(exc)
                LOGGER.exception("tenant engine %s failed to start",
                                 tenant_token)
                return None
            with self._lock:
                self.engines[tenant_token] = engine
                self.failed.pop(tenant_token, None)
            return engine
        finally:
            with self._lock:
                self._starting.discard(tenant_token)

    def stop_engine(self, tenant_token: str) -> None:
        with self._lock:
            engine = self.engines.pop(tenant_token, None)
            self._stopped.add(tenant_token)
        if engine is not None:
            engine.stop()

    def retire_engine(self, tenant_token: str) -> None:
        """Stop the engine for a DELETED tenant without leaving the
        admin-stop flag behind: an admin stop must survive stale async
        model-update records, but a deletion must not block a future
        tenant that legitimately reuses the token (tombstone resurrection
        semantics, multitenant/replication.py)."""
        self.stop_engine(tenant_token)
        with self._lock:
            self._stopped.discard(tenant_token)

    def restart_engine(self, tenant_token: str) -> Optional[TenantEngine]:
        self.stop_engine(tenant_token)
        return self.start_engine(tenant_token, force=True)

    # -- tenant-model-updates ---------------------------------------------
    def _on_updates(self, records: List) -> None:
        for record in records:
            try:
                update = json.loads(record.value)
            except Exception:
                continue
            token = update.get("tenant", "")
            operation = update.get("operation", "")
            if operation == "create":
                self.start_engine(token)
            elif operation == "delete":
                self.retire_engine(token)
            elif operation == "update":
                self.restart_engine(token)
